"""chaos-run: replay a named fault plan against a local cluster.

Spins up a master + N spawned worker processes over a temporary (or
given) db, runs the golden pipeline twice — once clean, once under the
chosen fault plan — and reports whether the fault fired and whether the
faulted run's output is bit-exact to the clean one.  The CLI twin of
tests/test_chaos.py, for poking a failure class by hand:

    python tools/chaos_run.py --list
    python tools/chaos_run.py worker-crash
    python tools/chaos_run.py unavailable-storm --rows 48 --workers 3
    python tools/chaos_run.py "pipeline.save:raise:exc=storage:n=3"

A plan name resolves via scanner_tpu.util.faults.NAMED_PLANS; anything
else is parsed as a raw plan spec (docs/robustness.md syntax).  Plans
whose sites live in the workers (pipeline.*, storage.*, gcs.*,
worker.*, rpc.server on workers is N/A) ship to ONE worker process via
SCANNER_TPU_FAULTS, so the sibling(s) stay healthy to absorb the
reassigned work; rpc.client.* / master-side plans arm in this process
(the client) or the master respectively.  A crashed master is
respawned once so recovery can be observed.

Exit codes: 0 = fault fired and output bit-exact; 1 = verification
failed; 2 = bad usage.
"""

import argparse
import os
import struct
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEFAULT_ROWS = 24


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="replay a named fault plan against a local cluster")
    ap.add_argument("plan", nargs="?",
                    help="named plan (see --list) or a raw plan spec")
    ap.add_argument("--list", action="store_true",
                    help="list the canned fault plans and exit")
    ap.add_argument("--db", default=None,
                    help="db path (default: a fresh temp dir)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--rows", type=int, default=N_DEFAULT_ROWS)
    ap.add_argument("--task-timeout", type=float, default=8.0,
                    help="per-task timeout for the faulted run (the "
                         "revocation safety net)")
    args = ap.parse_args()

    from scanner_tpu.util import faults

    if args.list:
        width = max(len(n) for n in faults.NAMED_PLANS)
        for name, spec in sorted(faults.NAMED_PLANS.items()):
            print(f"{name:<{width}}  {spec}")
        return 0
    if not args.plan:
        ap.error("a plan name or spec is required (or --list)")

    spec = faults.NAMED_PLANS.get(args.plan, args.plan)
    rules = faults.parse_plan(spec)  # validate before spinning anything
    sites = {r.site for r in rules}
    # memory.* sites hook device staging (engine/batch.py to_device):
    # they need a frame pipeline with a device kernel to have anything
    # to fire on, and the workers need device staging forced on the
    # CPU backend (SCANNER_TPU_KERNEL_DEVICES=all) — same lever the
    # multichip tests use
    mem_plan = any(s.split(".")[0] == "memory" for s in sites)
    # gang.* sites fire in the worker process (engine/gang.py
    # spawn_member), and a gang plan needs the bulk itself to run in
    # gang mode (PerfParams.gang_hosts) so there is a gang to lose
    gang_plan = any(s.split(".")[0] == "gang" for s in sites)
    worker_side = any(s.split(".")[0] in ("pipeline", "storage", "gcs",
                                          "worker", "memory", "gang")
                      for s in sites)
    master_side = "rpc.server.handle" in sites
    client_side = "rpc.client.call" in sites
    print(f"plan: {spec}\nsites: {sorted(sites)} "
          f"(worker={worker_side} master={master_side} "
          f"client={client_side})")

    import tempfile

    import cloudpickle

    import scanner_tpu  # noqa: F401 — registers builtin ops
    from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                             PerfParams, register_op)
    from scanner_tpu.util import metrics as _mx

    @register_op(name="ChaosRunDouble")
    class ChaosRunDouble(Kernel):
        def execute(self, x: bytes) -> bytes:
            time.sleep(0.1)
            return _pk(2 * struct.unpack("<q", x)[0])

    cloudpickle.register_pickle_by_value(sys.modules[__name__])

    db_path = args.db or tempfile.mkdtemp(prefix="chaos_run_")
    print(f"db: {db_path}")
    seed = Client(db_path=db_path)
    if mem_plan:
        import scanner_tpu.kernels  # noqa: F401 — registers Histogram
        from scanner_tpu import video as scv
        vid = os.path.join(tempfile.mkdtemp(prefix="chaos_vid_"),
                           "src.mp4")
        scv.synthesize_video(vid, num_frames=args.rows, width=64,
                             height=48, fps=24, keyint=8)
        seed.ingest_videos([("chaos_vid", vid)])
    else:
        seed.new_table("chaos_src", ["output"],
                       [[_pk(100 + i)] for i in range(args.rows)],
                       overwrite=True)

    # children run on the CPU backend with ambient accelerator-plugin
    # triggers stripped (util/jaxenv.py: a wedged tunnel would hang the
    # child at interpreter start) — same discipline as the test spawns
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    if mem_plan:
        env["SCANNER_TPU_KERNEL_DEVICES"] = "all"
    if gang_plan:
        # bounded rendezvous + a short formation hold so the drill's
        # re-form-on-survivors path resolves in seconds, not minutes
        env.setdefault("SCANNER_TPU_GANG_INIT_TIMEOUT", "30")
        env.setdefault("SCANNER_TPU_GANG_FORM_TIMEOUT", "6")

    def spawn(script, argv, plan=None, env_extra=None):
        e = dict(env)
        if plan:
            e["SCANNER_TPU_FAULTS"] = plan
        e.update(env_extra or {})
        return subprocess.Popen([sys.executable,
                                 os.path.join(REPO, "tests", script),
                                 *argv], env=e)

    import socket

    def _free_port() -> int:
        with socket.socket() as s:
            s.bind(("localhost", 0))
            return s.getsockname()[1]

    # the sharded-control-plane drill: three master shards instead of
    # one (docs/robustness.md §Sharded control plane).  The plan arms
    # in EVERY shard, but only the shard owning the bulk handles
    # FinishedWork — so exactly that shard dies, and the respawn (no
    # plan) fails the partition over in its shard namespace.
    shard_loss = args.plan == "master-shard-loss"
    num_shards = 3 if shard_loss else 1
    if shard_loss:
        env["SCANNER_TPU_CONTROL_SHARDS"] = str(num_shards)
    shard_ports = [_free_port() for _ in range(num_shards)]
    port = shard_ports[0]
    addr = f"localhost:{port}"

    procs = []
    shard_masters = {}
    for sid, p in enumerate(shard_ports):
        argv = [db_path, str(p)]
        if shard_loss:
            argv += [str(sid), str(num_shards)]
        m = spawn("spawn_master.py", argv,
                  plan=spec if master_side else None)
        shard_masters[sid] = m
        procs.append(m)
    master = shard_masters[0]
    for i in range(args.workers):
        # the FIRST worker carries a worker-side plan; siblings stay
        # healthy so reassigned work has somewhere to go
        procs.append(spawn("spawn_worker.py", [addr, db_path],
                           plan=spec if worker_side and i == 0 else None))

    respawned = {}
    if master_side and shard_loss:
        # per-shard crash watch: whichever shard the fault kills is
        # respawned under the SAME shard id + port, with no plan —
        # the respawn CAS-claims its shard's next generation and
        # replays its journal (shard failover)
        def watch_shard(sid: int):
            rc_ = shard_masters[sid].wait()
            if rc_ != faults.CRASH_EXIT_CODE:
                return
            respawned["rc"] = rc_
            respawned["shard"] = sid
            print(f"shard {sid} died (exit {rc_}); respawning")
            time.sleep(0.5)
            m2 = spawn("spawn_master.py",
                       [db_path, str(shard_ports[sid]), str(sid),
                        str(num_shards)])
            shard_masters[sid] = m2
            procs.append(m2)
        for sid in shard_masters:
            threading.Thread(target=watch_shard, args=(sid,),
                             daemon=True).start()
    elif master_side:
        def respawn_master():
            respawned["rc"] = master.wait()
            print(f"master died (exit {respawned['rc']}); respawning")
            time.sleep(0.5)
            m2 = spawn("spawn_master.py", [db_path, str(port)])
            respawned["proc"] = m2
            procs.append(m2)
        threading.Thread(target=respawn_master, daemon=True).start()

    from scanner_tpu.engine.rpc import wait_for_server
    from scanner_tpu.engine.service import MASTER_SERVICE
    wait_for_server(addr, MASTER_SERVICE, timeout=60.0)
    for p in shard_ports[1:]:
        # every shard must serve before the client resolves the map,
        # or the drill's routing would collapse onto the seed shard
        wait_for_server(f"localhost:{p}", MASTER_SERVICE, timeout=60.0)
    sc = Client(db_path=db_path, master=addr)
    # wait for every worker to register (subprocess import time
    # dominates); a worker-side plan can only fire on a joined worker
    deadline = time.time() + 60.0
    while time.time() < deadline:
        st = sc.job_status()
        if st.get("num_workers", 0) >= args.workers:
            break
        time.sleep(0.25)
    print(f"workers registered: {sc.job_status().get('num_workers', 0)}")

    def run(out_name, **kw):
        if mem_plan:
            from scanner_tpu import NamedVideoStream
            col = sc.io.Input([NamedVideoStream(sc, "chaos_vid")])
            col = sc.ops.Histogram(frame=col)
        else:
            col = sc.io.Input([NamedStream(sc, "chaos_src")])
            col = sc.ops.ChaosRunDouble(x=col)
        out = NamedStream(sc, out_name)
        if gang_plan:
            # gang mode: ~2 big tasks instead of rows/2 small ones —
            # each task costs a member-runner rendezvous, and two is
            # enough to prove loss + re-form + completion.  io must be
            # a work-packet multiple, so round rows/2 down to one
            # (floored at a single packet) for any --rows value.
            wp = 4
            io = max(wp, (args.rows // 2 // wp) * wp)
            perf = PerfParams.manual(wp, io, gang_hosts=2, **kw)
        else:
            perf = PerfParams.manual(2, 2, **kw)
        sc.run(sc.io.Output(col, [out]), perf,
               cache_mode=CacheMode.Overwrite, show_progress=True)
        return [bytes(r) for r in out.load()]

    # the master-failover drill leans on the write-ahead journal as
    # the ONLY durability (checkpoint_frequency=0) and adds a
    # stale-master fencing probe after the runs
    failover = args.plan == "master-failover"

    rc = 1
    try:
        # faulted run FIRST: worker/master-side plans armed via env are
        # live from process start, so running clean before them would
        # inject into the "clean" baseline.  After the faulted run the
        # victim is dead/deactivated or its fire budget is spent, and
        # the clean run sees an undisturbed cluster.
        if client_side:
            faults.install(spec)
        print("== faulted run ==")
        got = run("chaos_faulted", task_timeout=args.task_timeout,
                  checkpoint_frequency=0 if (failover or shard_loss)
                  else 1)
        # read the rule counters BEFORE clear() empties the registry —
        # client-side fires exist nowhere else (sc.metrics() aggregates
        # master+workers, not this process)
        local_fired = faults.fired()
        faults.clear()
        if shard_loss:
            # the plan is still ARMED in every surviving shard (each
            # process carries its own fire budget), so a clean bulk
            # that happened to hash onto an armed shard would crash it
            # too: replace the survivors with unarmed processes first.
            # (The victim's respawn is already unarmed.)
            time.sleep(1.0)  # let the crash watcher finish its respawn
            for sid, m_ in list(shard_masters.items()):
                if sid == respawned.get("shard"):
                    continue
                m_.kill()
                m_.wait()
                m2 = spawn("spawn_master.py",
                           [db_path, str(shard_ports[sid]), str(sid),
                            str(num_shards)])
                shard_masters[sid] = m2
                procs.append(m2)
            for p_ in shard_ports:
                wait_for_server(f"localhost:{p_}", MASTER_SERVICE,
                                timeout=60.0)
        print("== clean run ==")
        golden = run("chaos_clean", task_timeout=args.task_timeout)

        exact = got == golden
        # remote fires show up as worker/master death or in the
        # cluster-wide metric when the process is still alive
        snap = sc.metrics()
        entry = snap.get("scanner_tpu_faults_injected_total", {})
        cluster_fired = sum(s.get("value", 0)
                            for s in entry.get("samples", []))
        crashed = [p for p in procs
                   if p.poll() == faults.CRASH_EXIT_CODE]
        # a preempted worker drains and exits 0 BEFORE the metric poll,
        # taking its own faults counter with it — the master-side
        # preemption-notice counter is the surviving evidence
        preempt_notices = sum(
            s.get("value", 0) for s in snap.get(
                "scanner_tpu_worker_preempt_notices_total",
                {}).get("samples", []))
        print(f"\nfault fired: local={int(local_fired)} "
              f"cluster-metric={int(cluster_fired)} "
              f"injected-crashes={len(crashed)} "
              f"preempt-notices={int(preempt_notices)}")
        print(f"output bit-exact to clean run: {exact} "
              f"({len(got)} rows)")
        fired = bool(local_fired or cluster_fired or crashed
                     or preempt_notices
                     or respawned.get("rc") == faults.CRASH_EXIT_CODE)
        extra_ok = True
        if gang_plan:
            # gang-drill evidence (ISSUE acceptance): the gang aborted
            # on the injected host loss, RE-FORMED at a higher epoch on
            # the survivors, and no survivor ate a blacklist strike
            def _tot(name):
                return sum(s.get("value", 0) for s in
                           snap.get(name, {}).get("samples", []))

            formed = _tot("scanner_tpu_gang_formed_total")
            aborted = _tot("scanner_tpu_gang_aborted_total")
            reforms = _tot("scanner_tpu_gang_reforms_total")
            epoch = _tot("scanner_tpu_gang_epoch")
            strikes = _tot("scanner_tpu_blacklist_strikes_total")
            print(f"gang: formed={int(formed)} aborted={int(aborted)} "
                  f"reforms={int(reforms)} epoch={int(epoch)} "
                  f"strikes={int(strikes)}")
            # sharded-path evidence: gang_sharded defaults on, so the
            # drill's tasks evaluate mesh-partitioned — every shard
            # commit the master folded must agree ("ok"); any mismatch
            # or partial fold under member loss is a real divergence
            folds = snap.get("scanner_tpu_gang_shard_commit_folds_total",
                             {}).get("samples", [])
            fold_ok = sum(s.get("value", 0) for s in folds
                          if s.get("labels", {}).get("result") == "ok")
            fold_bad = sum(s.get("value", 0) for s in folds
                           if s.get("labels", {}).get("result") != "ok")
            shard_rows = _tot("scanner_tpu_gang_shard_rows_total")
            if shard_rows or folds:
                print(f"gang shard: rows={int(shard_rows)} "
                      f"folds ok={int(fold_ok)} non-ok={int(fold_bad)}")
            extra_ok = bool(aborted >= 1 and reforms >= 1
                            and epoch >= 2 and strikes == 0
                            and fold_bad == 0)
        if shard_loss:
            # shard-loss evidence (ISSUE acceptance): the killed
            # shard's respawn replayed its journal (failover replay >
            # 0) with ZERO journaled completions re-queued, no worker
            # ate a blacklist strike, and no shard's health roll-up is
            # left unhealthy (the survivors never were; the victim's
            # respawn recovered)
            def _tot(name):
                return sum(s.get("value", 0) for s in
                           snap.get(name, {}).get("samples", []))

            replayed = _tot("scanner_tpu_journal_replayed_records_total")
            failovers = _tot("scanner_tpu_shard_failovers_total")
            reexec = _tot("scanner_tpu_shard_journal_reexec_total")
            strikes = _tot("scanner_tpu_blacklist_strikes_total")
            from scanner_tpu.engine.rpc import RpcClient
            statuses = {}
            for sid, p_ in enumerate(shard_ports):
                probe = RpcClient(f"localhost:{p_}", MASTER_SERVICE,
                                  timeout=10.0)
                try:
                    h = probe.try_call("GetHealth", workers=False,
                                       timeout=10.0)
                finally:
                    probe.close()
                statuses[sid] = (h or {}).get("status")
            print(f"shard-loss: killed-shard={respawned.get('shard')} "
                  f"journal-replayed={int(replayed)} "
                  f"failovers={int(failovers)} reexec={int(reexec)} "
                  f"strikes={int(strikes)} shard-health={statuses}")
            extra_ok = bool(
                replayed > 0 and failovers >= 1 and reexec == 0
                and strikes == 0
                and respawned.get("rc") == faults.CRASH_EXIT_CODE
                and all(st is not None and st != "unhealthy"
                        for st in statuses.values()))
        if failover:
            # failover-specific evidence: the successor replayed the
            # journal, zero blacklist strikes anywhere, and a
            # forced-stale (generation-1) master is fenced with zero
            # accepted mutations
            def _tot(name):
                return sum(s.get("value", 0) for s in
                           snap.get(name, {}).get("samples", []))

            replayed = _tot("scanner_tpu_journal_replayed_records_total")
            strikes = _tot("scanner_tpu_blacklist_strikes_total")
            with socket.socket() as s2:
                s2.bind(("localhost", 0))
                port2 = s2.getsockname()[1]
            stale = spawn("spawn_master.py", [db_path, str(port2)],
                          env_extra={"SCANNER_TPU_MASTER_GENERATION":
                                     "1"})
            procs.append(stale)
            from scanner_tpu.engine.rpc import RpcClient
            wait_for_server(f"localhost:{port2}", MASTER_SERVICE,
                            timeout=60.0)
            probe = RpcClient(f"localhost:{port2}", MASTER_SERVICE,
                              timeout=10.0)
            try:
                fenced = all(
                    probe.call(m, **p).get("fenced")
                    for m, p in (
                        ("NewJob", {"spec": b"", "token": "t"}),
                        ("NextWork", {"worker_id": 0, "bulk_id": 0}),
                        ("FinishedWork", {"worker_id": 0, "bulk_id": 0,
                                          "job_idx": 0, "task_idx": 0,
                                          "attempt": 0})))
            finally:
                probe.close()
            print(f"failover: journal-replayed={int(replayed)} "
                  f"strikes={int(strikes)} stale-master-fenced={fenced}")
            extra_ok = bool(replayed > 0 and strikes == 0 and fenced)
        rc = 0 if (exact and fired and extra_ok) else 1
        if not fired:
            print("WARNING: no evidence the fault fired — plan matched "
                  "nothing?")
    finally:
        sc.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
