"""scanner-top: live cluster telemetry in a terminal.

Polls the master's GetJobStatus + GetMetrics RPCs and renders a
per-job / per-node table — the interactive consumer of the telemetry
subsystem (docs/observability.md).  `top` for a scanner cluster:

    python tools/scanner_top.py --master localhost:5000
    python tools/scanner_top.py --master localhost:5000 --once   # scripts
    python tools/scanner_top.py --master localhost:5000 --json   # machines

Rates (decode fps, eval rows/s, h2d MB/s) come from counter deltas
between polls; the first poll (and --once) uses since-process-start
averages via scanner_tpu_process_start_time_seconds.  Exit codes:
0 ok, 2 master unreachable.
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# -- snapshot digestion -----------------------------------------------------

def _sum_counter(snap: dict, name: str, node: str) -> float:
    """Sum a counter's samples for one node across its other labels."""
    entry = snap.get(name)
    if not entry:
        return 0.0
    return sum(s.get("value", 0.0) for s in entry["samples"]
               if s["labels"].get("node") == node)


def _gauge(snap: dict, name: str, node: str, **labels) -> float:
    entry = snap.get(name)
    if not entry:
        return 0.0
    for s in entry["samples"]:
        sl = s["labels"]
        if sl.get("node") == node and all(sl.get(k) == v
                                          for k, v in labels.items()):
            return s.get("value", 0.0)
    return 0.0


def _nodes(snap: dict):
    seen = []
    for entry in snap.values():
        for s in entry["samples"]:
            n = s["labels"].get("node")
            if n and n not in seen:
                seen.append(n)
    return sorted(seen)


# -- sharded control plane fan-in -------------------------------------------

def _resolve_shards(client):
    """{shard_id: address} from GetShardMap on the dialed master, or
    None for a single-master cluster (docs/robustness.md §Sharded
    control plane).  Every shard serves the full versioned map, so the
    --master address may name any live shard."""
    reply = client.try_call("GetShardMap", retries=1)
    if not reply or int(reply.get("num_shards", 1) or 1) <= 1:
        return None
    shards = {int(k): v for k, v in (reply.get("shards") or {}).items()}
    return shards or None


def _poll_sharded(shard_clients: dict):
    """Fan GetMetrics/GetJobStatus/GetHealth across every shard.

    Mirrors ClusterClient's fan-in: each shard's master samples relabel
    to shard<k> before merging (per-shard control-plane series stay
    distinguishable in the NODE table), workers ride the lowest live
    shard only (every shard sees the same fleet — M pulls would skew
    the merged counters M-fold), and health folds worst-of via
    health.merge_status so one degraded shard degrades the roll-up.

    Returns (merged_snapshot | None, status, health, shard_rows) —
    snapshot None when no shard answered at all.
    """
    from scanner_tpu.util.health import merge_status
    from scanner_tpu.util.metrics import merge_snapshots

    sids = sorted(shard_clients)
    primary = sids[0]
    by_node, rows, status, healths = {}, [], None, {}
    for sid in sids:
        c = shard_clients[sid]
        node = f"shard{sid}"
        reply = c.try_call("GetMetrics", retries=1, timeout=30.0,
                           workers=(sid == primary))
        row = {"shard": sid, "addr": c.address, "up": reply is not None}
        if reply and "snapshot" in reply:
            snap = reply["snapshot"]
            for entry in snap.values():
                for s in entry.get("samples", []):
                    lb = s.get("labels") or {}
                    if lb.get("node") == "master":
                        s["labels"] = dict(lb, node=node)
            by_node[node] = snap
            row["map_epoch"] = _gauge(
                snap, "scanner_tpu_shard_map_epoch", node)
            row["failovers"] = _sum_counter(
                snap, "scanner_tpu_shard_failovers_total", node)
            row["stale_map_rejections"] = _sum_counter(
                snap, "scanner_tpu_shard_stale_map_rejections_total", node)
            row["rpcs_coalesced"] = _sum_counter(
                snap, "scanner_tpu_rpc_coalesced_total", node)
        # the bulk lives on exactly one shard: first shard that knows a
        # live bulk wins (the rest answer "no active bulk")
        st = c.try_call("GetJobStatus", bulk_id=None, retries=0)
        if status is None and st and "tasks_done" in st:
            status = st
        h = c.try_call("GetHealth", retries=0, workers=(sid == primary))
        healths[node] = h if h else {
            "status": "unhealthy", "reasons": ["shard_unreachable"],
            "firing": []}
        rows.append(row)
    health = merge_status(healths)
    return (merge_snapshots(by_node) if by_node else None,
            status, health, rows)


NODE_COUNTERS = {
    "decode_f": "scanner_tpu_decoded_frames_total",
    "eval_r": "scanner_tpu_op_rows_total",
    "h2d_b": "scanner_tpu_h2d_bytes_total",
    "d2h_b": "scanner_tpu_d2h_bytes_total",
    "retries": "scanner_tpu_retry_attempts_total",
}


def _sum_by_label(snap: dict, name: str, node: str, label: str) -> dict:
    """{label_value: summed value} for one node's samples of a series."""
    entry = snap.get(name)
    if not entry:
        return {}
    out = {}
    for s in entry["samples"]:
        sl = s["labels"]
        if sl.get("node") == node and label in sl:
            out[sl[label]] = out.get(sl[label], 0.0) + s.get("value", 0.0)
    return out


def _op_efficiency(snap: dict, node: str) -> dict:
    """{(op, device): row} from the coststats efficiency gauges,
    keeping the largest bucket per (op, device) — the steady-state
    rung (tail buckets run rarely and noisy)."""
    out = {}
    entry = snap.get("scanner_tpu_op_efficiency_ratio")
    if not entry:
        return out
    for s in entry["samples"]:
        sl = s["labels"]
        if sl.get("node") != node:
            continue
        key = (sl.get("op", "?"), sl.get("device", "?"))
        try:
            bucket = int(sl.get("bucket", 0))
        except ValueError:
            bucket = 0
        if key in out and out[key]["bucket"] >= bucket:
            continue
        labels = {"op": key[0], "device": key[1],
                  "bucket": sl.get("bucket", "0")}
        out[key] = {
            "bucket": bucket,
            "efficiency": s.get("value", 0.0),
            "compute_bound": _gauge(
                snap, "scanner_tpu_op_compute_bound", node,
                **labels) >= 0.5,
            "flops_per_s": _gauge(
                snap, "scanner_tpu_op_achieved_flops", node, **labels),
            "bytes_per_s": _gauge(
                snap, "scanner_tpu_op_achieved_bandwidth_bytes", node,
                **labels),
        }
    return out


def _per_device(snap: dict, name: str, node: str) -> dict:
    """{device: value} for one node's samples of a device-labeled
    series (multi-chip evaluator affinity)."""
    entry = snap.get(name)
    if not entry:
        return {}
    out = {}
    for s in entry["samples"]:
        sl = s["labels"]
        if sl.get("node") == node and "device" in sl:
            out[sl["device"]] = out.get(sl["device"], 0.0) \
                + s.get("value", 0.0)
    return out


def digest(snap: dict) -> dict:
    """Per-node counter totals + gauges + a timestamp, ready for rate
    computation between two polls."""
    out = {"t": time.time(), "nodes": {}}
    for node in _nodes(snap):
        d = {k: _sum_counter(snap, name, node)
             for k, name in NODE_COUNTERS.items()}
        d["start"] = _gauge(snap, "scanner_tpu_process_start_time_seconds",
                            node)
        d["evalq"] = _gauge(snap, "scanner_tpu_stage_queue_depth", node,
                            stage="evaluate")
        d["saveq"] = _gauge(snap, "scanner_tpu_stage_queue_depth", node,
                            stage="save")
        # per-chip utilization (evaluator affinity): tasks + busy
        # seconds per assigned device — the series the tool predated;
        # without them a wedged chip hides inside the node totals
        d["dev_tasks"] = _per_device(
            snap, "scanner_tpu_device_tasks_total", node)
        d["dev_busy"] = _per_device(
            snap, "scanner_tpu_device_busy_seconds_total", node)
        # per-chip memory (util/memstats.py): backend-reported HBM
        # occupancy/limit plus the allocation ledger's engine-owned
        # live bytes (summed across buffer kinds)
        d["dev_hbm"] = _per_device(
            snap, "scanner_tpu_device_hbm_bytes_in_use", node)
        d["dev_hbm_limit"] = _per_device(
            snap, "scanner_tpu_device_hbm_limit_bytes", node)
        d["dev_ledger"] = _per_device(
            snap, "scanner_tpu_ledger_live_bytes", node)
        # paged frame cache (engine/framecache.py): resident page bytes
        # and hit/miss counters per device — a hot-clip workload should
        # show CACHE MB climbing and CHIT% approaching 100
        d["dev_cache"] = _per_device(
            snap, "scanner_tpu_framecache_live_bytes", node)
        d["dev_cache_hits"] = _per_device(
            snap, "scanner_tpu_framecache_hits_total", node)
        d["dev_cache_misses"] = _per_device(
            snap, "scanner_tpu_framecache_misses_total", node)
        # compute-efficiency plane (util/coststats.py): XLA compiles by
        # persistent-cache outcome, and the per-(op, device) roofline
        # verdict at the steady-state bucket
        d["compile"] = _sum_by_label(
            snap, "scanner_tpu_compile_total", node, "cache")
        d["ops"] = _op_efficiency(snap, node)
        out["nodes"][node] = d
    return out


def _hit_rate(compile_by_cache: dict):
    """Persistent-cache hit rate, or None when no cache is configured
    (every observed compile was `uncached`)."""
    hit = compile_by_cache.get("hit", 0.0)
    miss = compile_by_cache.get("miss", 0.0)
    return hit / (hit + miss) if (hit + miss) else None


def _rate(cur: dict, prev: dict, key: str, now: float) -> float:
    """delta/interval vs the previous poll, or since-start average."""
    if prev is not None:
        dt = max(cur["_dt"], 1e-6)
        return max(cur[key] - prev.get(key, 0.0), 0.0) / dt
    up = max(now - cur["start"], 1e-6) if cur.get("start") else None
    return cur[key] / up if up else 0.0


# -- rendering --------------------------------------------------------------

def render(status: dict, cur: dict, prev: dict, master: str,
           health: dict = None, shards: list = None) -> str:
    now = cur["t"]
    lines = [f"scanner-top  master={master}  "
             f"{time.strftime('%H:%M:%S', time.localtime(now))}"]
    if status is None or "tasks_done" not in status:
        lines.append("no active bulk job")
    else:
        fps = status.get("stage_fps") or {}
        eta = status.get("eta_seconds")
        lines.append(
            f"bulk: {status['tasks_done']}/{status['total_tasks']} tasks"
            f"  workers={status.get('num_workers', '?')}"
            f"  load {fps.get('load', 0):.1f} r/s"
            f"  eval {fps.get('evaluate', 0):.1f} r/s"
            f"  save {fps.get('save', 0):.1f} r/s"
            + (f"  ETA {eta:.0f}s" if eta is not None else "")
            + ("  FINISHED" if status.get("finished") else ""))
        per_job = status.get("per_job") or {}
        lagging = [(j, d) for j, d in sorted(per_job.items())
                   if d["tasks_done"] < d["tasks_total"]]
        if len(per_job) > 1:
            shown = lagging[:8]
            lines.append(f"jobs: {len(per_job)} total, "
                         f"{len(per_job) - len(lagging)} complete"
                         + ("; in flight: " + ", ".join(
                             f"#{j} {d['tasks_done']}/{d['tasks_total']}"
                             + (" [blacklisted]" if d.get("blacklisted")
                                else "")
                             for j, d in shown) if shown else ""))
    # per-shard control-plane columns (docs/robustness.md §Sharded
    # control plane): one row per master shard — map epoch divergence,
    # failover replays, stale-map NACKs and RPC coalescing per shard.
    # A dead shard renders UP=NO instead of silently vanishing.
    if shards:
        lines.append("")
        lines.append(f"{'SHARD':>5} {'ADDR':20} {'UP':>3} {'EPOCH':>6} "
                     f"{'FAILOVER':>9} {'STALEMAP':>9} {'COALESCED':>10}")
        for r in shards:
            if r.get("up"):
                lines.append(
                    f"{r['shard']:>5} {str(r.get('addr', '?')):20} "
                    f"{'yes':>3} {r.get('map_epoch', 0):>6.0f} "
                    f"{r.get('failovers', 0):>9.0f} "
                    f"{r.get('stale_map_rejections', 0):>9.0f} "
                    f"{r.get('rpcs_coalesced', 0):>10.0f}")
            else:
                lines.append(
                    f"{r['shard']:>5} {str(r.get('addr', '?')):20} "
                    f"{'NO':>3} {'-':>6} {'-':>9} {'-':>9} {'-':>10}")
    lines.append("")
    hdr = (f"{'NODE':10} {'DECODE f/s':>10} {'EVAL r/s':>9} "
           f"{'H2D MB/s':>9} {'D2H MB/s':>9} {'EVALQ':>6} {'SAVEQ':>6} "
           f"{'RETRY':>6}")
    lines.append(hdr)
    prev_nodes = (prev or {}).get("nodes", {})
    for node, d in sorted(cur["nodes"].items()):
        p = prev_nodes.get(node)
        if p is not None:
            d["_dt"] = cur["t"] - prev["t"]
        lines.append(
            f"{node:10} "
            f"{_rate(d, p, 'decode_f', now):>10.1f} "
            f"{_rate(d, p, 'eval_r', now):>9.1f} "
            f"{_rate(d, p, 'h2d_b', now) / 1e6:>9.2f} "
            f"{_rate(d, p, 'd2h_b', now) / 1e6:>9.2f} "
            f"{d['evalq']:>6.0f} {d['saveq']:>6.0f} "
            f"{d['retries']:>6.0f}")
    # per-chip breakdown (evaluator affinity + memstats): one row per
    # (node, device) that has taken tasks or holds memory — chip
    # imbalance (a device stuck while siblings climb) and HBM skew are
    # invisible in the node totals above
    dev_rows = []
    for node, d in sorted(cur["nodes"].items()):
        tasks_by = d.get("dev_tasks") or {}
        devs = set(tasks_by) | set(d.get("dev_hbm") or {}) \
            | set(d.get("dev_ledger") or {})
        if not devs or (devs == {"default"} and not d.get("dev_hbm")):
            continue
        p = prev_nodes.get(node) or {}
        for dev in sorted(devs):
            tasks = tasks_by.get(dev, 0.0)
            busy = (d.get("dev_busy") or {}).get(dev, 0.0)
            p_busy = (p.get("dev_busy") or {}).get(dev, 0.0)
            if "_dt" in d:
                util = max(busy - p_busy, 0.0) / max(d["_dt"], 1e-6)
            else:
                up = max(now - d["start"], 1e-6) if d.get("start") else None
                util = busy / up if up else 0.0
            hbm = (d.get("dev_hbm") or {}).get(dev, 0.0)
            limit = (d.get("dev_hbm_limit") or {}).get(dev, 0.0)
            ledger = (d.get("dev_ledger") or {}).get(dev, 0.0)
            pct = f"{hbm / limit * 100:>5.1f}%" if limit else "    -"
            cache_mb = (d.get("dev_cache") or {}).get(dev, 0.0) / 1e6
            chits = (d.get("dev_cache_hits") or {}).get(dev, 0.0)
            cmiss = (d.get("dev_cache_misses") or {}).get(dev, 0.0)
            chit = f"{chits / (chits + cmiss) * 100:>5.1f}%" \
                if chits + cmiss else "    -"
            dev_rows.append(
                f"{node:10} {dev:>10} {tasks:>7.0f} {busy:>8.1f} "
                f"{min(util, 1.0) * 100:>6.1f}% {hbm / 1e6:>9.1f} "
                f"{pct:>6} {ledger / 1e6:>9.1f} {cache_mb:>9.1f} "
                f"{chit:>6}")
    if dev_rows:
        lines.append("")
        lines.append(f"{'NODE':10} {'DEVICE':>10} {'TASKS':>7} "
                     f"{'BUSY s':>8} {'UTIL':>7} {'HBM MB':>9} "
                     f"{'HBM%':>6} {'LEDG MB':>9} {'CACHE MB':>9} "
                     f"{'CHIT%':>6}")
        lines.extend(dev_rows)
    # per-op roofline (util/coststats.py): EFF% against the device peak
    # for the binding resource, at the steady-state bucket — a slow op
    # at high EFF% needs more chips, at low EFF% a better kernel.  The
    # XCACHE column is the node's persistent-compile-cache hit rate.
    eff_rows = []
    # fused chain ids ("a+b+c") outgrow the classic 16-char op column:
    # size it to the widest label in this snapshot
    opw = max([16] + [len(op) for _, d in cur["nodes"].items()
                      for (op, _dev) in (d.get("ops") or {})])
    for node, d in sorted(cur["nodes"].items()):
        ops = d.get("ops") or {}
        hr = _hit_rate(d.get("compile") or {})
        hr_s = f"{hr * 100:.0f}%" if hr is not None else "-"
        for (op, dev), o in sorted(ops.items()):
            eff_rows.append(
                f"{node:10} {op:{opw}} {dev:>9} {o['bucket']:>6} "
                f"{o['efficiency'] * 100:>6.1f}% "
                f"{'compute' if o['compute_bound'] else 'memory':>8} "
                f"{o['flops_per_s'] / 1e9:>9.2f} "
                f"{o['bytes_per_s'] / 1e9:>8.3f} {hr_s:>6}")
    if eff_rows:
        lines.append("")
        lines.append(f"{'NODE':10} {'OP':{opw}} {'DEVICE':>9} "
                     f"{'BUCKET':>6} "
                     f"{'EFF%':>7} {'BOUND':>8} {'GFLOP/s':>9} "
                     f"{'GB/s':>8} {'XCACHE':>6}")
        lines.extend(eff_rows)
    # GANG SKEW (docs/observability.md §Cross-host time): per-gang
    # straggler attribution from the master's barrier-arrival fold —
    # which host made each gang slow, by how much, and whether the
    # step was barrier-bound (a late arrival) or collective-bound
    skew = ((status or {}).get("stragglers") or {}).get("gangs") or []
    if skew:
        lines.append("")
        lines.append(f"GANG SKEW{'':5} {'GANG':>5} {'EPOCH':>5} "
                     f"{'SKEW ms':>8} {'SLOWEST':>10} {'LAG ms':>7} "
                     f"{'BOUND':>10}")
        for g in skew[:8]:
            lines.append(
                f"{'':14} {str(g.get('gang')):>5} "
                f"{str(g.get('epoch')):>5} "
                f"{g.get('skew_s', 0) * 1e3:>8.1f} "
                f"{str(g.get('slowest')):>10} "
                f"{g.get('lag_s', 0) * 1e3:>7.1f} "
                f"{str(g.get('bound')):>10}")
    # cluster health (GetHealth): the judgment layer — which rules fire
    # where, so "is it healthy" doesn't require reading the counters
    if health:
        firing = health.get("firing") or []
        if firing:
            lines.append("")
            lines.append(f"ALERTS ({health.get('status', '?')})")
            for f in firing[:10]:
                lbl = ",".join(
                    f"{k}={v}" for k, v in
                    sorted((f.get("labels") or {}).items()))
                since = f.get("since")
                age = f"{max(now - since, 0):.0f}s" if since else "-"
                lines.append(
                    f"  {str(f.get('node', '-')):10} "
                    f"{f.get('rule', '?'):24} "
                    f"{f.get('severity', '?'):8} {lbl:24} {age:>6}")
            if len(firing) > 10:
                lines.append(f"  ... and {len(firing) - 10} more")
        elif health.get("status") == "ok":
            lines.append("")
            lines.append("health: ok (0 alerts firing)")
    return "\n".join(lines)


def json_doc(status: dict, cur: dict, master: str,
             health: dict = None, shards: list = None) -> dict:
    """The --json document: everything --once renders, machine-readable
    (scripts used to scrape the human table).  Per-node counter totals
    since process start plus the per-device utilization/memory maps."""
    nodes = {}
    for node, d in sorted(cur["nodes"].items()):
        nodes[node] = {
            "decoded_frames": d["decode_f"],
            "eval_rows": d["eval_r"],
            "h2d_bytes": d["h2d_b"],
            "d2h_bytes": d["d2h_b"],
            "retries": d["retries"],
            "eval_queue": d["evalq"],
            "save_queue": d["saveq"],
            "process_start_time": d.get("start"),
            "devices": {
                dev: {
                    "tasks": (d.get("dev_tasks") or {}).get(dev, 0.0),
                    "busy_seconds":
                        (d.get("dev_busy") or {}).get(dev, 0.0),
                    "hbm_bytes_in_use":
                        (d.get("dev_hbm") or {}).get(dev, 0.0),
                    "hbm_limit_bytes":
                        (d.get("dev_hbm_limit") or {}).get(dev, 0.0),
                    "ledger_live_bytes":
                        (d.get("dev_ledger") or {}).get(dev, 0.0),
                    "framecache_live_bytes":
                        (d.get("dev_cache") or {}).get(dev, 0.0),
                    "framecache_hits":
                        (d.get("dev_cache_hits") or {}).get(dev, 0.0),
                    "framecache_misses":
                        (d.get("dev_cache_misses") or {}).get(dev, 0.0),
                }
                for dev in sorted(set(d.get("dev_tasks") or {})
                                  | set(d.get("dev_hbm") or {})
                                  | set(d.get("dev_ledger") or {})
                                  | set(d.get("dev_cache") or {}))
            },
            # compute-efficiency plane: compile counts by cache outcome
            # (+ derived hit rate) and the per-op roofline rows the
            # human table renders
            "compile": dict(d.get("compile") or {},
                            hit_rate=_hit_rate(d.get("compile") or {})),
            "ops": {
                f"{op}@{dev}": o
                for (op, dev), o in sorted((d.get("ops") or {}).items())
            },
        }
    return {"time": cur["t"], "master": master, "status": status,
            "health": health, "nodes": nodes,
            # sharded control plane: one entry per master shard with
            # map epoch / failover / stale-map / coalescing columns
            # (None for a single-master cluster)
            "shards": shards,
            # per-gang straggler attribution (also inside
            # status.stragglers.gangs; surfaced top-level so scripts
            # need not know the straggler summary's shape)
            "gang_skew": ((status or {}).get("stragglers") or {})
            .get("gangs") or []}


# -- main -------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live per-job/per-worker telemetry for a scanner_tpu "
                    "cluster (top-style)")
    ap.add_argument("--master", default="localhost:5000",
                    help="master address host:port (default %(default)s)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll period seconds (default %(default)s)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (for scripts)")
    ap.add_argument("--json", action="store_true",
                    help="print one machine-readable JSON snapshot and "
                         "exit (mirrors --once; no table scraping)")
    args = ap.parse_args(argv)

    from scanner_tpu.engine.rpc import RpcClient
    from scanner_tpu.engine.service import MASTER_SERVICE

    client = RpcClient(args.master, MASTER_SERVICE, timeout=10.0)
    # sharded control plane: resolve the versioned shard map from the
    # dialed master (any shard serves it) and dial every shard — the
    # poll loop then fans in instead of assuming one master
    shard_addrs = _resolve_shards(client)
    shard_clients = {}
    if shard_addrs:
        for sid, addr in sorted(shard_addrs.items()):
            shard_clients[sid] = client if addr == client.address \
                else RpcClient(addr, MASTER_SERVICE, timeout=10.0)
    prev = None
    try:
        while True:
            shard_rows = None
            if shard_clients:
                snap, status, health, shard_rows = \
                    _poll_sharded(shard_clients)
                if snap is None:
                    print(f"scanner-top: no shard of {args.master} "
                          f"reachable", file=sys.stderr)
                    return 2
            else:
                reply = client.try_call("GetMetrics", retries=1)
                if reply is None:
                    print(f"scanner-top: master {args.master} "
                          f"unreachable", file=sys.stderr)
                    return 2
                snap = reply["snapshot"]
                status = client.try_call("GetJobStatus", bulk_id=None,
                                         retries=1)
                # cluster-wide health roll-up + firing alerts
                # (GetHealth); best-effort like the status poll
                health = client.try_call("GetHealth", retries=0)
            if status is not None and "error" in status \
                    and "tasks_done" not in status:
                status = None
            cur = digest(snap)
            if args.json:
                import json as _json
                print(_json.dumps(json_doc(status, cur, args.master,
                                           health, shard_rows)))
                return 0
            frame = render(status, cur, prev, args.master, health,
                           shard_rows)
            if args.once:
                print(frame)
                return 0
            # clear screen + home, like top
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            prev = cur
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        for c in shard_clients.values():
            if c is not client:
                c.close()
        client.close()


if __name__ == "__main__":
    sys.exit(main())
