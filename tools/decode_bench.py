"""Decode-scaling bench: N loader threads x libav decoder_threads vs fps.

The engine's design claim is "N GIL-free decoders feed one TPU"
(cpp/scvid.cpp header; reference decoder_cpus / load-worker pools,
worker.cpp:1631) — this tool puts a measured curve behind it on any
host.  Each loader thread owns one DecoderAutomata (one codec handle)
and decodes a distinct row range of the same ingested stream; the C
calls release the GIL, so throughput should scale with threads until
cores (or memory bandwidth) saturate.

Run: python tools/decode_bench.py [--frames N] [--width W] [--height H]
Prints one JSON line per (loaders, decoder_threads) config and writes
DECODE_BENCH.json; the PERF.md scaling table is transcribed from it.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--frames", type=int, default=384)
    ap.add_argument("--width", type=int, default=640)
    ap.add_argument("--height", type=int, default=480)
    ap.add_argument("--keyint", type=int, default=32)
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args()

    from scanner_tpu.storage import Database, PosixStorage
    from scanner_tpu import video as scv

    ncpu = os.cpu_count() or 1
    root = tempfile.mkdtemp(prefix="decbench_")
    vid = os.path.join(root, "clip.mp4")
    scv.synthesize_video(vid, num_frames=args.frames, width=args.width,
                         height=args.height, fps=30, keyint=args.keyint)
    db = Database(PosixStorage(os.path.join(root, "db")))
    _, failed = scv.ingest_videos(db, [("clip", vid)])
    assert not failed, failed

    def run_cfg(n_loaders: int, dec_threads: int) -> float:
        """Aggregate fps: each loader decodes a keyint-ALIGNED share of
        the stream (every loader seeks to a keyframe, like engine
        tasks).  Shares are dealt GOP by GOP round-robin so every
        loader gets work even when ceil-division would starve the last
        ones (n_loaders must match the thread count the row claims)."""
        n_gops = -(-args.frames // args.keyint)
        assert n_loaders <= n_gops, \
            f"{n_loaders} loaders need >= {n_loaders} GOPs " \
            f"(have {n_gops}; raise --frames)"
        shares = [[] for _ in range(n_loaders)]
        for g in range(n_gops):
            lo = g * args.keyint
            hi = min(args.frames, lo + args.keyint)
            shares[g % n_loaders].extend(range(lo, hi))
        autos = [scv.open_automata(db, "clip", n_threads=dec_threads)
                 for _ in shares]
        try:
            best = float("inf")
            for _ in range(args.reps):
                done = []
                errs = []

                def work(a, rows):
                    try:
                        got = a.get_frames(rows)
                        done.append(len(got))
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                ts = [threading.Thread(target=work, args=(a, r))
                      for a, r in zip(autos, shares)]
                t0 = time.time()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                dt = time.time() - t0
                if errs:
                    raise errs[0]
                assert sum(done) == args.frames
                best = min(best, dt)
            return args.frames / best
        finally:
            for a in autos:
                a.close()

    configs = []
    for n_loaders in (1, 2, 4, 8):
        if n_loaders > max(ncpu * 2, 2):
            break
        configs.append((n_loaders, 1))
    for dec_threads in (2, 4):
        if dec_threads <= ncpu:
            configs.append((1, dec_threads))
    if ncpu >= 4:
        configs.append((2, 2))

    out = {"host_cpus": ncpu, "frames": args.frames,
           "geometry": f"{args.width}x{args.height}",
           "keyint": args.keyint,
           "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), "rows": []}
    base = None
    for n_loaders, dec_threads in configs:
        fps = run_cfg(n_loaders, dec_threads)
        if base is None:
            base = fps
        row = {"loaders": n_loaders, "decoder_threads": dec_threads,
               "fps": round(fps, 1), "speedup": round(fps / base, 2)}
        out["rows"].append(row)
        print(json.dumps(row), flush=True)
    with open(os.path.join(REPO, "DECODE_BENCH.json"), "w") as f:
        json.dump(out, f, indent=1)
    import shutil
    shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
