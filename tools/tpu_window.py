"""Everything to run in one healthy tunnel window, in priority order.

The accelerator tunnel on this host is intermittently healthy; this tool
banks ALL pending hardware evidence the moment a window opens:

  1. full bench + microbench capture (tools/tpu_capture.py --force)
  2. native pallas flash-attention A/B vs the XLA attention block
  3. a profiled config-1 pipeline run: Chrome trace artifact
     (PERF_TRACE_TPU.json) + stage-overlap summary — the measured
     proof that decode (load stage) overlaps device compute
  4. pose-config stage attribution (model-resident fps + per-stage wall)
  5. per-op device/host A/B over the kernel stdlib + model zoo
     (tools/op_bench.py -> OP_BENCH.json)

Results are appended to TPU_WINDOW.json; the trace artifact and the A/B
numbers feed PERF.md.  Run: python tools/tpu_window.py
Exit codes: 0 all steps ran (individual failures recorded in the json),
2 tunnel down.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_WINDOW.json")

_ATTN_AB = r"""
import json, time, functools
import numpy as np, jax, jax.numpy as jnp
from scanner_tpu.kernels.pallas_attention import flash_block_update, NEG_INF
out = {"device": str(jax.devices()[0])}

BH, T, D = 16, 2048, 128   # 16 heads, 2k-token block, head dim 128
rng = np.random.RandomState(0)
q = jax.device_put(rng.randn(BH, T, D).astype(np.float32) * (D ** -0.5))
k = jax.device_put(rng.randn(BH, T, D).astype(np.float32))
v = jax.device_put(rng.randn(BH, T, D).astype(np.float32))
m0 = jnp.full((BH, T), NEG_INF, jnp.float32)
l0 = jnp.zeros((BH, T), jnp.float32)
a0 = jnp.zeros((BH, T, D), jnp.float32)

@functools.partial(jax.jit, static_argnames=("causal",))
def xla_block(q, k, v, m, l, acc, causal=False):
    logits = jnp.einsum("bqd,bkd->bqk", q, k)
    if causal:
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        logits = jnp.where(mask[None], logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(-1))
    p = jnp.exp(logits - m_new[..., None])
    corr = jnp.exp(jnp.where(m <= NEG_INF / 2, 0.0, m - m_new))
    corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
    l_new = l * corr + p.sum(-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bqk,bkd->bqd", p, v)
    return m_new, l_new, acc_new

def force(res):
    return float(jax.device_get(sum(jnp.sum(x) for x in res)))

def bench(name, fn, reps=20):
    try:
        force(fn())
    except Exception as e:
        out[name] = f"FAILED {type(e).__name__}: {str(e)[:200]}"
        return
    t0 = time.time()
    acc = None
    for _ in range(reps):
        r = fn()
        s = sum(jnp.sum(x) for x in r)
        acc = s if acc is None else acc + s
    _ = float(jax.device_get(acc))
    dt = (time.time() - t0) / reps
    # 2 matmuls of BH*T*T*D MACs each
    tflops = 2 * 2 * BH * T * T * D / dt / 1e12
    out[name] = {"ms": round(dt * 1000, 2), "tflops": round(tflops, 2)}

for causal in (False, True):
    sfx = "_causal" if causal else ""
    bench(f"pallas_flash{sfx}",
          lambda c=causal: flash_block_update(q, k, v, m0, l0, a0, 0, 0,
                                              causal=c))
    bench(f"xla_block{sfx}",
          lambda c=causal: xla_block(q, k, v, m0, l0, a0, causal=c))
# equivalence on hardware
try:
    pm, plv, pa = flash_block_update(q, k, v, m0, l0, a0, 0, 0)
    xm, xl, xa = xla_block(q, k, v, m0, l0, a0)
    po = jax.device_get(pa / jnp.maximum(plv[..., None], 1e-30))
    xo = jax.device_get(xa / jnp.maximum(xl[..., None], 1e-30))
    out["max_abs_diff"] = float(np.abs(po - xo).max())
except Exception as e:
    out["max_abs_diff"] = f"FAILED {type(e).__name__}"
print("ATTN_AB " + json.dumps(out))
"""

_TRACE_RUN = r"""
import json, os, shutil, sys, tempfile, time
import atexit
import numpy as np
root = tempfile.mkdtemp(prefix="sctrace_")
atexit.register(lambda: shutil.rmtree(root, ignore_errors=True))
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
import jax
assert jax.devices()[0].platform == "tpu"
N, W, H = 600, 640, 480
vid = os.path.join(root, "bench.mp4")
scv.synthesize_video(vid, num_frames=N, width=W, height=H, fps=30,
                     keyint=32)
sc = Client(db_path=os.path.join(root, "db"), num_load_workers=3,
            num_save_workers=1)
_, _ing_failed = sc.ingest_videos([("bench", vid)])
assert not _ing_failed, _ing_failed

def run(name, level=1):
    frames = sc.io.Input([NamedVideoStream(sc, "bench")])
    ranged = sc.streams.Range(frames, [(0, N)])
    out = NamedStream(sc, name)
    t0 = time.time()
    job = sc.run(sc.io.Output(sc.ops.Histogram(frame=ranged), [out]),
                 PerfParams.manual(32, 96, profiler_level=level),
                 cache_mode=CacheMode.Overwrite,
                 show_progress=False)
    return job, time.time() - t0

run("warm")
# fps is measured UNTRACED (level 1) — level-2 capture + synchronous XLA
# trace export would skew the wall; the trace artifact comes from a
# separate traced run of the same job
job, dt = run("meas")
tjob, dt_traced = run("traced", level=2)
prof = sc.get_profile(tjob)
prof.write_trace("PERF_TRACE_TPU.json")  # cwd = repo root (runner sets it)
stats = sc.get_profile(job).statistics()
# stage overlap: wall vs sum of exclusive stage time.  If load (decode)
# fully overlapped evaluate, wall ~= max(load, evaluate) not their sum.
load_s = stats.get("load", {}).get("total_s", 0.0)
eval_s = stats.get("evaluate", {}).get("total_s", 0.0)
save_s = stats.get("save", {}).get("total_s", 0.0)
summary = {
    "fps": round(N / dt, 1), "wall_s": round(dt, 2),
    "load_total_s": round(load_s, 2),
    "evaluate_total_s": round(eval_s, 2),
    "save_total_s": round(save_s, 2),
    "sum_stages_s": round(load_s + eval_s + save_s, 2),
    "overlap_ratio": round((load_s + eval_s + save_s) / max(dt, 1e-9), 2),
    "wall_s_traced": round(dt_traced, 2),
}
print("TRACE_SUMMARY " + json.dumps(summary))
sc.stop()
"""

# Config 3 (pose) ran at 11 fps on capture 2 — far below what the chip's
# matmul rate predicts.  This step attributes its wall per stage AND
# isolates the on-device model cost (forced completion) so the next
# healthy window answers whether the gap is decode, h2d, dispatch
# granularity, or the model itself.
_TRACE_POSE = r"""
import json, os, shutil, tempfile, time
import atexit
import numpy as np
root = tempfile.mkdtemp(prefix="scpose_")
atexit.register(lambda: shutil.rmtree(root, ignore_errors=True))
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.models
from scanner_tpu import video as scv
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == "tpu"
summary = {}

# on-device model microbench: PoseDetect width-8 infer on resident frames
from scanner_tpu.graph.ops import registry, KernelConfig
from scanner_tpu.common import DeviceType
cfg = KernelConfig(device=DeviceType.TPU, devices=[jax.devices()[0]])
kern = registry.get("PoseDetect").kernel_factory(cfg, width=8)
imgs = jax.device_put(np.random.randint(0, 255, (16, 480, 640, 3),
                                        dtype=np.uint8))
out = kern.execute(imgs)
_ = np.asarray(jax.device_get(jnp.sum(jnp.asarray(out))))
t0 = time.time()
reps = 10
acc = None
for _i in range(reps):
    r = jnp.sum(jnp.asarray(kern.execute(imgs)))
    acc = r if acc is None else acc + r
_ = float(jax.device_get(acc))
dt = (time.time() - t0) / reps
summary["model_fps_resident"] = round(16 / dt, 1)

N, W, H = 128, 640, 480
vid = os.path.join(root, "bench.mp4")
scv.synthesize_video(vid, num_frames=N, width=W, height=H, fps=30,
                     keyint=32)
sc = Client(db_path=os.path.join(root, "db"), num_load_workers=3,
            num_save_workers=1)
_, _ing_failed = sc.ingest_videos([("bench", vid)])
assert not _ing_failed, _ing_failed

def run(name, level=1):
    frames = sc.io.Input([NamedVideoStream(sc, "bench")])
    ranged = sc.streams.Range(frames, [(0, N)])
    out = NamedStream(sc, name)
    t0 = time.time()
    job = sc.run(sc.io.Output(sc.ops.PoseDetect(frame=ranged, width=8),
                              [out]),
                 PerfParams.manual(32, 96, profiler_level=level),
                 cache_mode=CacheMode.Overwrite,
                 show_progress=False)
    return job, time.time() - t0

run("warm")
# untraced fps; the device-trace artifact comes from a separate run
job, dt = run("meas")
tjob, _dtt = run("traced", level=2)
sc.get_profile(tjob).write_trace("PERF_TRACE_POSE_TPU.json")
stats = sc.get_profile(job).statistics()
summary.update({
    "fps": round(N / dt, 1), "wall_s": round(dt, 2),
    "load_total_s": round(stats.get("load", {}).get("total_s", 0.0), 2),
    "evaluate_total_s": round(
        stats.get("evaluate", {}).get("total_s", 0.0), 2),
    "save_total_s": round(stats.get("save", {}).get("total_s", 0.0), 2),
})
print("POSE_TRACE " + json.dumps(summary))
sc.stop()
"""


_R5_AB = r"""
import json, os, shutil, tempfile, time
import atexit
root = tempfile.mkdtemp(prefix="scr5ab_")
atexit.register(lambda: shutil.rmtree(root, ignore_errors=True))
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
import jax
assert jax.devices()[0].platform == "tpu"
N, W, H = 600, 640, 480
vid = os.path.join(root, "bench.mp4")
scv.synthesize_video(vid, num_frames=N, width=W, height=H, fps=30,
                     keyint=32)
sc = Client(db_path=os.path.join(root, "db"), num_load_workers=3,
            num_save_workers=1)
_, failed = sc.ingest_videos([("bench", vid)])
assert not failed, failed

def run(name, yuv, stream):
    os.environ["SCANNER_TPU_YUV_DEVICE"] = "1" if yuv else "0"
    frames = sc.io.Input([NamedVideoStream(sc, "bench")])
    ranged = sc.streams.Range(frames, [(0, N)])
    out = NamedStream(sc, name)
    t0 = time.time()
    sc.run(sc.io.Output(sc.ops.Histogram(frame=ranged), [out]),
           PerfParams.manual(32, 96, stream_work_packets=stream),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return round(N / (time.time() - t0), 1)

out = {}
run("warm", True, True)  # compile + page-cache warmup
# isolate each round-5 lever on hardware: YUV wire (h2d bytes) and
# work-packet streaming (decode/h2d/compute overlap within tasks)
out["fps_yuv_stream"] = run("ys", True, True)
out["fps_rgb_stream"] = run("rs", False, True)
out["fps_yuv_whole"] = run("yw", True, False)
out["fps_rgb_whole"] = run("rw", False, False)
os.environ.pop("SCANNER_TPU_YUV_DEVICE", None)
print("R5_AB " + json.dumps(out))
sc.stop()
"""


_MC_UTIL = r"""
import json, os, tempfile, time
import jax
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
from scanner_tpu.util.metrics import labeled_samples, registry

root = tempfile.mkdtemp(prefix="mc_hw_")
vid = os.path.join(root, "v.mp4")
N = 384
scv.synthesize_video(vid, num_frames=N, width=640, height=480, fps=24,
                     keyint=32)
sc = Client(db_path=os.path.join(root, "db"))
sc.ingest_videos([("bench", vid)])

def run(name):
    frames = sc.io.Input([NamedVideoStream(sc, "bench")])
    out = NamedStream(sc, name)
    t0 = time.time()
    sc.run(sc.io.Output(sc.ops.Histogram(frame=frames), [out]),
           PerfParams.manual(32, 96), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    return round(N / (time.time() - t0), 1)

def series(name):
    return labeled_samples(registry().snapshot(), name)

run("mc_warm")  # compile + page-cache warmup on every chip
base_busy = series("scanner_tpu_device_busy_seconds_total")
base_tasks = series("scanner_tpu_device_tasks_total")
fps_aff = run("mc_aff")
busy = series("scanner_tpu_device_busy_seconds_total")
tasks = series("scanner_tpu_device_tasks_total")
os.environ["SCANNER_TPU_DEVICE_AFFINITY"] = "0"   # the A/B lever
fps_off = run("mc_off")
out = {
    "n_devices": len(jax.local_devices()),
    "fps_affinity": fps_aff,
    "fps_no_affinity": fps_off,
    "device_tasks": {k: tasks.get(k, 0) - base_tasks.get(k, 0)
                     for k in tasks},
    "device_busy_seconds": {
        k: round(busy.get(k, 0) - base_busy.get(k, 0), 3) for k in busy},
}
sc.stop()
# bank the per-device utilization digest with the round's bench
# evidence (the same file bench.py writes its digests to)
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "multichip_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **out})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("MULTICHIP_UTIL " + json.dumps(out))
"""


_EFF_DIGEST = r"""
import json, os, tempfile, time
import jax
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
from scanner_tpu.util import coststats

root = tempfile.mkdtemp(prefix="eff_hw_")
vid = os.path.join(root, "v.mp4")
N = 384
scv.synthesize_video(vid, num_frames=N, width=640, height=480, fps=24,
                     keyint=32)
sc = Client(db_path=os.path.join(root, "db"))
sc.ingest_videos([("bench", vid)])

def run(name, build):
    frames = sc.io.Input([NamedVideoStream(sc, "bench")])
    out = NamedStream(sc, name)
    t0 = time.time()
    sc.run(sc.io.Output(build(frames), [out]), PerfParams.manual(32, 96),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return round(N / (time.time() - t0), 1)

# warm first so the measured runs are compile-free: the roofline join
# excludes compile-bearing first calls, but the fps numbers should be
# steady-state too
run("eff_warm", lambda f: sc.ops.Histogram(frame=f))
fps_hist = run("eff_hist", lambda f: sc.ops.Histogram(frame=f))
fps_blur = run("eff_blur", lambda f: sc.ops.Blur(frame=f))
out = {
    "device": str(jax.devices()[0]),
    "fps_histogram": fps_hist,
    "fps_blur": fps_blur,
    "ops": coststats.op_efficiency(),
    "compile": coststats.ledger_summary(),
}
sc.stop()
# bank the hardware roofline digest with the round's bench evidence
# (same file bench.py writes its digests to) — the ROADMAP asks for a
# hardware op_efficiency baseline on the next healthy capture window
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "op_efficiency_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **out})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("EFF_DIGEST " + json.dumps(out))
"""


_FRAMECACHE_AB = r"""
import json, os, tempfile, time
import jax
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
from scanner_tpu.engine import framecache
from scanner_tpu.util.metrics import registry

assert jax.devices()[0].platform == "tpu"
root = tempfile.mkdtemp(prefix="fc_hw_")
vid = os.path.join(root, "v.mp4")
N = 384
scv.synthesize_video(vid, num_frames=N, width=640, height=480, fps=24,
                     keyint=32)
sc = Client(db_path=os.path.join(root, "db"))
sc.ingest_videos([("bench", vid)])

def tot(name):
    s = registry().snapshot().get(name, {})
    return sum(x["value"] for x in s.get("samples", []))

def run(name):
    frames = sc.io.Input([NamedVideoStream(sc, "bench")])
    out = NamedStream(sc, name)
    d0, b0 = tot("scanner_tpu_decode_seconds_total"), \
        tot("scanner_tpu_h2d_bytes_total")
    t0 = time.time()
    sc.run(sc.io.Output(sc.ops.Histogram(frame=frames), [out]),
           PerfParams.manual(32, 96), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    return {"fps": round(N / (time.time() - t0), 1),
            "decode_s": round(
                tot("scanner_tpu_decode_seconds_total") - d0, 3),
            "h2d_bytes": tot("scanner_tpu_h2d_bytes_total") - b0}

framecache.cache().clear()
h0, m0 = tot("scanner_tpu_framecache_hits_total"), \
    tot("scanner_tpu_framecache_misses_total")
on_cold = run("fc_cold")
h1, m1 = tot("scanner_tpu_framecache_hits_total"), \
    tot("scanner_tpu_framecache_misses_total")
on_warm = run("fc_warm")
hits = tot("scanner_tpu_framecache_hits_total") - h0
misses = tot("scanner_tpu_framecache_misses_total") - m0
wh = tot("scanner_tpu_framecache_hits_total") - h1
wm = tot("scanner_tpu_framecache_misses_total") - m1
framecache.set_enabled(False)
off = run("fc_off")
framecache.set_enabled(True)
out = {
    "device": str(jax.devices()[0]),
    "frames": N,
    "hit_rate": round(hits / (hits + misses), 4) if hits + misses else None,
    "warm_hit_rate": round(wh / (wh + wm), 4) if wh + wm else None,
    "on_cold": on_cold, "on_warm": on_warm, "off": off,
    "decode_seconds_saved": round(off["decode_s"] - on_warm["decode_s"], 3),
    "h2d_bytes_saved": off["h2d_bytes"] - on_warm["h2d_bytes"],
    "framecache": framecache.status_dict(),
}
sc.stop()
# bank the hardware frame-cache digest with the round's bench evidence
# (same file bench.py writes its digests to) — the ISSUE asks for a
# frame_cache_hw baseline on the next healthy capture window
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "frame_cache_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **out})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("FRAMECACHE_AB " + json.dumps(out))
"""


_GANG_HW = r"""
import json, os, struct, subprocess, sys, tempfile, time

# libtpu is single-process-exclusive per chip: the cluster (master +
# 2 workers + their member-runner children) cannot share the TPU with
# this script, and two concurrent children could not share it with
# each other.  The gang_hw digest measures what the hardware window
# adds — formation/reform latency on the real host (its kernel, net
# stack, and process-spawn costs) — so the member math runs on the CPU
# backend while the TPU device identity is probed in a throwaway
# subprocess with the ambient env.
probe = subprocess.run(
    [sys.executable, "-c",
     "import jax; d = jax.devices()[0]; print(d.platform, d)"],
    capture_output=True, text=True, timeout=300)
tpu_dev = probe.stdout.strip()
assert tpu_dev.startswith("tpu"), f"no TPU: {tpu_dev or probe.stderr[-200:]}"
os.environ["JAX_PLATFORMS"] = "cpu"

import cloudpickle, jax
from scanner_tpu import CacheMode, Client, Kernel, NamedStream, PerfParams, \
    register_op
from scanner_tpu.engine import gang as egang
from scanner_tpu.engine.service import Master, Worker
from scanner_tpu.util.metrics import registry

def pk(v):
    return struct.pack("<q", v)

@register_op(name="GangHwDouble")
class GangHwDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return pk(2 * struct.unpack("<q", x)[0])

cloudpickle.register_pickle_by_value(sys.modules["__main__"])

def tot(name):
    s = registry().snapshot().get(name, {})
    return sum(x["value"] for x in s.get("samples", []))

root = tempfile.mkdtemp(prefix="gang_hw_")
N = 16
sc = Client(db_path=os.path.join(root, "db"))
sc.new_table("gang_src", ["output"], [[pk(100 + i)] for i in range(N)])
m = Master(db_path=os.path.join(root, "db"), no_workers_timeout=120.0)
addr = f"localhost:{m.port}"
egang.set_form_timeout_s(4.0)
workers = [Worker(addr, db_path=os.path.join(root, "db"))
           for _ in range(2)]
gc = Client(db_path=os.path.join(root, "db"), master=addr)
col = gc.io.Input([NamedStream(gc, "gang_src")])
col = gc.ops.GangHwDouble(x=col)
out = NamedStream(gc, "gang_out")
t0 = time.time()
gc.run(gc.io.Output(col, [out]), PerfParams.manual(4, 4, gang_hosts=2),
       cache_mode=CacheMode.Overwrite, show_progress=False)
elapsed = round(time.time() - t0, 2)
rows = [bytes(r) for r in out.load()]
res = {
    "device": tpu_dev,
    "members_on": "cpu (libtpu is single-process-exclusive)",
    "rows_ok": rows == [pk(2 * (100 + i)) for i in range(N)],
    "elapsed_s": elapsed,
    "gangs_formed": tot("scanner_tpu_gang_formed_total"),
    "gangs_aborted": tot("scanner_tpu_gang_aborted_total"),
    "epoch": tot("scanner_tpu_gang_epoch"),
}
gc.stop()
for w in workers:
    w.stop()
m.stop()
# bank the hardware gang digest with the round's bench evidence (same
# file bench.py writes its digests to) — the ISSUE asks for a gang_hw
# baseline on the next healthy capture window
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "gang_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **res})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("GANG_HW " + json.dumps(res))
"""

_GANG_SHARDED_HW = r"""
import json, os, struct, subprocess, sys, tempfile, time

# hardware companion to bench.py's gang_sharded digest: the
# mesh-partitioned A/B on the real host — one stencil bulk over a
# 2-host gang run replicated (every member evaluates all rows) then
# sharded (each member evaluates only its shard_range; boundary rows
# ride the halo exchange) — banking the stage-phase speedup, per-host
# decode rows, and halo bytes.  Same single-process-exclusive
# constraint as gang_hw: the TPU identity is probed in a throwaway
# subprocess, the member math runs on the CPU backend — what the
# hardware window adds is the real host's decode/spawn/net behavior
# under the sharded data plane.
probe = subprocess.run(
    [sys.executable, "-c",
     "import jax; d = jax.devices()[0]; print(d.platform, d)"],
    capture_output=True, text=True, timeout=300)
tpu_dev = probe.stdout.strip()
assert tpu_dev.startswith("tpu"), f"no TPU: {tpu_dev or probe.stderr[-200:]}"
os.environ["JAX_PLATFORMS"] = "cpu"

from typing import Sequence

import cloudpickle, jax
import numpy as np
from scanner_tpu import CacheMode, Client, FrameType, Kernel, \
    NamedStream, NamedVideoStream, PerfParams, register_op
from scanner_tpu import video as scv
from scanner_tpu.engine import gang as egang
from scanner_tpu.engine.service import Master, Worker
from scanner_tpu.util.metrics import registry

def pk(v):
    return struct.pack("<q", v)

@register_op(name="GangShardHwStencil", stencil=[-1, 0])
class GangShardHwStencil(Kernel):
    def execute(self, frame: Sequence[FrameType]) -> bytes:
        time.sleep(0.05)
        return pk(int(np.asarray(frame, np.int64).sum()))

cloudpickle.register_pickle_by_value(sys.modules["__main__"])

def stage_by_role():
    fam = registry().snapshot().get(
        "scanner_tpu_gang_phase_seconds_total", {})
    return {s["labels"].get("role"): s["value"]
            for s in fam.get("samples", [])
            if s["labels"].get("phase") == "stage"}

def tot(name):
    s = registry().snapshot().get(name, {})
    return sum(x["value"] for x in s.get("samples", []))

root = tempfile.mkdtemp(prefix="gang_sharded_hw_")
N = 16
vid = os.path.join(root, "v.mp4")
scv.synthesize_video(vid, num_frames=N, width=64, height=48, fps=24,
                     keyint=8)
sc = Client(db_path=os.path.join(root, "db"))
sc.ingest_videos([("gshard_vid", vid)])
m = Master(db_path=os.path.join(root, "db"), no_workers_timeout=120.0)
addr = f"localhost:{m.port}"
egang.set_form_timeout_s(6.0)
workers = [Worker(addr, db_path=os.path.join(root, "db"))
           for _ in range(2)]
gc = Client(db_path=os.path.join(root, "db"), master=addr)

def run_mode(mode, sharded):
    st0 = stage_by_role()
    hb0 = tot("scanner_tpu_gang_shard_halo_bytes_total")
    col = gc.io.Input([NamedVideoStream(gc, "gshard_vid")])
    col = gc.ops.GangShardHwStencil(frame=col)
    out = NamedStream(gc, f"gshard_{mode}")
    t0 = time.time()
    gc.run(gc.io.Output(col, [out]),
           PerfParams.manual(4, 8, gang_hosts=2, gang_sharded=sharded),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    wall = round(time.time() - t0, 3)
    rows = len(list(out.load()))
    st1 = stage_by_role()
    stage = max((st1.get(r, 0.0) - st0.get(r, 0.0) for r in st1),
                default=0.0)
    return {"mode": mode, "rows_ok": rows == N, "wall_s": wall,
            "stage_s": round(stage, 3),
            "stage_rows_per_s": (round(rows / stage, 3)
                                 if stage > 0 else None),
            "halo_bytes": tot(
                "scanner_tpu_gang_shard_halo_bytes_total") - hb0}

rep = run_mode("replicated", False)
sha = run_mode("sharded", True)
speedup = None
if rep["stage_rows_per_s"] and sha["stage_rows_per_s"]:
    speedup = round(sha["stage_rows_per_s"] / rep["stage_rows_per_s"], 3)
decode = {s["labels"].get("role"): s["value"]
          for s in registry().snapshot().get(
              "scanner_tpu_gang_shard_decode_rows_total",
              {}).get("samples", [])}
res = {
    "device": tpu_dev,
    "members_on": "cpu (libtpu is single-process-exclusive)",
    "rows_ok": rep["rows_ok"] and sha["rows_ok"],
    "replicated": rep,
    "sharded": sha,
    "gang_sharded_speedup": speedup,
    "decode_rows_by_member": decode,
}
gc.stop()
for w in workers:
    w.stop()
m.stop()
# bank the hardware sharded digest next to bench.py's digests so
# tools/bench_history.py folds gang_sharded_hw into its section
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "gang_sharded_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **res})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("GANG_SHARDED_HW " + json.dumps(res))
"""

_GANG_SKEW_HW = r"""
import json, os, struct, subprocess, sys, tempfile, time

# hardware companion to bench.py's gang_skew digest: a CLEAN 2-worker
# gang run on the real host (no injected loss), banking the barrier
# skew p99 the master folded from offset-corrected member arrivals and
# the worst clock-offset uncertainty a worker published
# (util/clocksync.py).  Same single-process-exclusive constraint as
# gang_hw: the TPU identity is probed in a throwaway subprocess, the
# member math runs on the CPU backend — what the hardware window adds
# is the real host's clock/net/spawn behavior under the NTP-style
# heartbeat exchange.
probe = subprocess.run(
    [sys.executable, "-c",
     "import jax; d = jax.devices()[0]; print(d.platform, d)"],
    capture_output=True, text=True, timeout=300)
tpu_dev = probe.stdout.strip()
assert tpu_dev.startswith("tpu"), f"no TPU: {tpu_dev or probe.stderr[-200:]}"
os.environ["JAX_PLATFORMS"] = "cpu"

import cloudpickle, jax
from scanner_tpu import CacheMode, Client, Kernel, NamedStream, PerfParams, \
    register_op
from scanner_tpu.engine import gang as egang
from scanner_tpu.engine.service import Master, Worker
from scanner_tpu.util.metrics import registry, \
    snapshot_histogram_quantiles

def pk(v):
    return struct.pack("<q", v)

@register_op(name="GangSkewHwSleep")
class GangSkewHwSleep(Kernel):
    def execute(self, x: bytes) -> bytes:
        time.sleep(0.05)
        return pk(3 * struct.unpack("<q", x)[0])

cloudpickle.register_pickle_by_value(sys.modules["__main__"])

root = tempfile.mkdtemp(prefix="gang_skew_hw_")
N = 16
sc = Client(db_path=os.path.join(root, "db"))
sc.new_table("gskew_src", ["output"], [[pk(200 + i)] for i in range(N)])
m = Master(db_path=os.path.join(root, "db"), no_workers_timeout=120.0)
addr = f"localhost:{m.port}"
egang.set_form_timeout_s(4.0)
workers = [Worker(addr, db_path=os.path.join(root, "db"))
           for _ in range(2)]
gc = Client(db_path=os.path.join(root, "db"), master=addr)
col = gc.io.Input([NamedStream(gc, "gskew_src")])
col = gc.ops.GangSkewHwSleep(x=col)
out = NamedStream(gc, "gskew_out")
t0 = time.time()
gc.run(gc.io.Output(col, [out]), PerfParams.manual(4, 4, gang_hosts=2),
       cache_mode=CacheMode.Overwrite, show_progress=False)
elapsed = round(time.time() - t0, 2)
rows = [bytes(r) for r in out.load()]
with m._lock:
    b = m._bulk
    if b is None and m._history:
        b = m._history[max(m._history)]
    skew_rows = list(b.gang_skew_rows) if b is not None else []
# the uncertainty gauge needs ~2 heartbeat round-trips; bounded wait
deadline = time.time() + 10
while time.time() < deadline:
    if registry().snapshot().get(
            "scanner_tpu_clock_offset_uncertainty_seconds",
            {}).get("samples"):
        break
    time.sleep(0.1)
snap = registry().snapshot()
skq = snapshot_histogram_quantiles(
    snap, "scanner_tpu_gang_barrier_skew_seconds")
unc = [s["value"] for s in snap.get(
    "scanner_tpu_clock_offset_uncertainty_seconds",
    {}).get("samples", [])]
res = {
    "device": tpu_dev,
    "members_on": "cpu (libtpu is single-process-exclusive)",
    "rows_ok": rows == [pk(3 * (200 + i)) for i in range(N)],
    "elapsed_s": elapsed,
    "gang_barrier_skew_p99_s": skq.get("p99_s"),
    "gang_barrier_skew_p50_s": skq.get("p50_s"),
    "skews_observed": skq.get("count"),
    "clock_offset_uncertainty_s": (round(max(unc), 6) if unc else None),
    "gang_skew_rows": skew_rows[-4:],
}
gc.stop()
for w in workers:
    w.stop()
m.stop()
# bank the hardware skew digest next to bench.py's digests so
# tools/bench_history.py folds gang_skew_hw into its gang_skew section
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "gang_skew_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **res})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("GANG_SKEW_HW " + json.dumps(res))
"""


_FUSION_HW = r"""
import json, os, tempfile, time
import jax
from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                        PerfParams)
import scanner_tpu.kernels
from scanner_tpu import video as scv
from scanner_tpu.graph import fusion as _fusion
from scanner_tpu.util.metrics import registry

# hardware companion to bench.py's fusion digest: the golden
# Resize->Blur->Histogram->HistDiff pipeline staged vs fused on the
# real chip.  On TPU the fused chain keeps intermediates in HBM-
# resident registers/VMEM across member boundaries, so this is where
# the paper-shaped bandwidth win (not just the engine bookkeeping win
# the CPU capture measures) lands.
assert jax.devices()[0].platform == "tpu"
root = tempfile.mkdtemp(prefix="fz_hw_")
vid = os.path.join(root, "v.mp4")
N, W, H = 96, 640, 480
scv.synthesize_video(vid, num_frames=N, width=W, height=H, fps=24,
                     keyint=24)
sc = Client(db_path=os.path.join(root, "db"))
sc.ingest_videos([("fz_vid", vid)])
cid = "Resize+Blur+Histogram"
keys = (cid, "Resize", "Blur", "Histogram", "HistDiff")

def _by_op(name):
    out = {}
    for s in registry().snapshot().get(name, {}).get("samples", []):
        k = s["labels"].get("op", "_")
        out[k] = out.get(k, 0.0) + s["value"]
    return out

def run_mode(mode, on):
    prev = _fusion.enabled()
    _fusion.set_enabled(on)
    try:
        s0 = _by_op("scanner_tpu_op_seconds_total")
        r0 = _by_op("scanner_tpu_op_recompiles_total")
        col = sc.io.Input([NamedVideoStream(sc, "fz_vid")])
        col = sc.ops.Resize(frame=col, width=[W // 2], height=[H // 2])
        col = sc.ops.Blur(frame=col, kernel_size=3, sigma=1.1)
        col = sc.ops.Histogram(frame=col)
        col = sc.ops.HistDiff(frame=col)
        out = NamedStream(sc, f"fz_{mode}")
        t0 = time.time()
        sc.run(sc.io.Output(col, [out]), PerfParams.manual(8, 16),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        wall = round(time.time() - t0, 3)
        s1 = _by_op("scanner_tpu_op_seconds_total")
        r1 = _by_op("scanner_tpu_op_recompiles_total")
        return {"mode": mode, "wall_s": wall,
                "rows_ok": len(list(out.load())) == N,
                "op_seconds": round(sum(
                    s1.get(k, 0.0) - s0.get(k, 0.0) for k in keys), 4),
                "executables_minted": int(sum(
                    r1.get(k, 0) - r0.get(k, 0) for k in keys))}
    finally:
        _fusion.set_enabled(prev)

# cold pass mints executables; warm pass is the banked steady state
staged = run_mode("staged", False)
fused = run_mode("fused", True)
staged_w = run_mode("staged_warm", False)
fused_w = run_mode("fused_warm", True)
speedup = None
if staged_w["op_seconds"] and fused_w["op_seconds"]:
    speedup = round(staged_w["op_seconds"] / fused_w["op_seconds"], 3)
res = {
    "device": str(jax.devices()[0]),
    "chain": cid,
    "rows_ok": all(r["rows_ok"] for r in
                   (staged, fused, staged_w, fused_w)),
    "staged": staged, "fused": fused,
    "staged_warm": staged_w, "fused_warm": fused_w,
    "fused_chain_speedup": speedup,
    "executables_avoided": staged["executables_minted"]
                           - fused["executables_minted"],
}
sc.stop()
# bank the hardware fusion digest next to bench.py's digests so
# tools/bench_history.py folds fusion_hw into its fusion section
path = os.path.join(os.getcwd(), "BENCH_DETAIL.json")
try:
    detail = json.load(open(path))
    if not isinstance(detail, list):
        detail = [detail]
except Exception:
    detail = []
detail.append({"config": "fusion_hw",
               "clock": time.strftime("%Y-%m-%dT%H:%M:%S"), **res})
with open(path, "w") as f:
    json.dump(detail, f, indent=1)
print("FUSION_HW " + json.dumps(res))
"""


def tunnel_up() -> bool:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tpu_capture import tunnel_up as probe  # same probe + env override
    return probe()


def run_step(name, argv=None, code=None, timeout=1800, marker=None):
    print(f"== {name}", flush=True)
    try:
        cmd = argv or [sys.executable, "-c", code]
        r = subprocess.run(cmd, cwd=REPO, timeout=timeout,
                           capture_output=True, text=True)
        out_lines = r.stdout.strip().splitlines()
        if marker:
            for ln in out_lines:
                if ln.startswith(marker):
                    return json.loads(ln[len(marker):])
        if r.returncode != 0:
            return {"error": r.stderr[-1500:]}
        return {"ok": True, "tail": out_lines[-3:]}
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    except Exception as e:  # noqa: BLE001
        return {"error": repr(e)}


def main() -> int:
    if not tunnel_up():
        print("tunnel down")
        return 2
    results = {"started_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
    results["bench_capture"] = run_step(
        "bench capture",
        argv=[sys.executable, "tools/tpu_capture.py", "--force"],
        timeout=3300)  # > capture's own probe(90) + micro(600) + bench(2400)
    results["attention_ab"] = run_step(
        "pallas flash attention native A/B", code=_ATTN_AB,
        timeout=900, marker="ATTN_AB ")
    results["overlap_trace"] = run_step(
        "profiled pipeline trace", code=_TRACE_RUN,
        timeout=900, marker="TRACE_SUMMARY ")
    results["pose_trace"] = run_step(
        "pose config stage attribution", code=_TRACE_POSE,
        timeout=900, marker="POSE_TRACE ")
    results["round5_ab"] = run_step(
        "YUV-wire x streaming isolation A/B (config 1)", code=_R5_AB,
        timeout=1200, marker="R5_AB ")
    results["multichip_util"] = run_step(
        "per-device utilization digest + affinity A/B (-> "
        "BENCH_DETAIL.json)", code=_MC_UTIL,
        timeout=1200, marker="MULTICHIP_UTIL ")
    results["op_efficiency"] = run_step(
        "hardware roofline digest (util/coststats.py -> "
        "BENCH_DETAIL.json op_efficiency_hw)", code=_EFF_DIGEST,
        timeout=1200, marker="EFF_DIGEST ")
    results["frame_cache"] = run_step(
        "paged frame-cache cross-task reuse A/B (engine/framecache.py "
        "-> BENCH_DETAIL.json frame_cache_hw)", code=_FRAMECACHE_AB,
        timeout=1200, marker="FRAMECACHE_AB ")
    results["gang"] = run_step(
        "gang-scheduled multi-host bulk on hardware (engine/gang.py "
        "-> BENCH_DETAIL.json gang_hw)", code=_GANG_HW,
        timeout=1200, marker="GANG_HW ")
    results["gang_sharded"] = run_step(
        "sharded-vs-replicated gang A/B on hardware (engine/gang.py "
        "sharded body -> BENCH_DETAIL.json gang_sharded_hw)",
        code=_GANG_SHARDED_HW, timeout=1200, marker="GANG_SHARDED_HW ")
    results["gang_skew"] = run_step(
        "clean gang barrier-skew + clock-sync digest on hardware "
        "(util/clocksync.py -> BENCH_DETAIL.json gang_skew_hw)",
        code=_GANG_SKEW_HW, timeout=1200, marker="GANG_SKEW_HW ")
    results["fusion_hw"] = run_step(
        "whole-pipeline fusion staged-vs-fused A/B on hardware "
        "(graph/fusion.py -> BENCH_DETAIL.json fusion_hw)",
        code=_FUSION_HW, timeout=1200, marker="FUSION_HW ")
    results["op_bench"] = run_step(
        "per-op device/host A/B (tools/op_bench.py -> OP_BENCH.json)",
        argv=[sys.executable, "tools/op_bench.py"], timeout=1200)
    results["finished_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    history = []
    if os.path.exists(OUT):
        try:
            history = json.load(open(OUT))
            if not isinstance(history, list):
                history = [history]
        except Exception:
            history = []
    history.append(results)
    with open(OUT, "w") as f:
        json.dump(history, f, indent=1)
    print(json.dumps(results, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
