"""scanner-trace: dump and analyze a bulk's merged cross-host trace.

The CLI consumer of the distributed-tracing subsystem
(scanner_tpu/util/tracing.py, docs/observability.md §Tracing): pulls the
master-assembled span tree of a bulk (GetTrace RPC — every worker's
task/stage/op spans plus the master's scheduling spans, one trace_id per
job) and either writes a Perfetto/Chrome JSON, prints straggler
analytics, or audits chain completeness.

    python tools/scanner_trace.py --master localhost:5000 -o bulk.json
    python tools/scanner_trace.py --master localhost:5000 --bulk 3 --top 10
    python tools/scanner_trace.py --master localhost:5000 --verify

Exit codes: 0 ok, 1 incomplete chains (--verify), 2 master unreachable /
no such bulk.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fmt_stragglers(trace_id: str, s: dict) -> str:
    lines = [f"trace {trace_id}: {s.get('spans', 0)} spans"
             + (f" ({s['spans_dropped']} dropped)"
                if s.get("spans_dropped") else "")]
    per = s.get("per_stage") or {}
    if per:
        lines.append(f"{'STAGE':>20} {'COUNT':>7} {'TOTAL s':>9} "
                     f"{'MEAN s':>8} {'MAX s':>8}")
        for name, st in per.items():
            lines.append(f"{name:>20} {st['count']:>7} "
                         f"{st['total_s']:>9.3f} {st['mean_s']:>8.4f} "
                         f"{st['max_s']:>8.4f}")
    slow = s.get("slowest_tasks") or []
    if slow:
        lines.append("")
        lines.append(f"{'SLOWEST':>8} {'JOB':>4} {'TASK':>5} "
                     f"{'SECONDS':>8} {'NODE':>9}  SPAN")
        for i, t in enumerate(slow):
            lines.append(f"{'#%d' % (i + 1):>8} {str(t['job']):>4} "
                         f"{str(t['task']):>5} {t['seconds']:>8.3f} "
                         f"{str(t['node']):>9}  {t['span_id']}")
    gangs = s.get("gangs") or []
    if gangs:
        lines.append("")
        lines.append(f"{'GANG':>5} {'EPOCH':>5} {'SKEW ms':>8} "
                     f"{'SLOWEST':>10} {'LAG ms':>7} {'BOUND':>10}")
        for g in gangs:
            lines.append(
                f"{str(g.get('gang')):>5} {str(g.get('epoch')):>5} "
                f"{g.get('skew_s', 0) * 1e3:>8.1f} "
                f"{str(g.get('slowest')):>10} "
                f"{g.get('lag_s', 0) * 1e3:>7.1f} "
                f"{str(g.get('bound')):>10}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="dump/analyze a bulk's merged cross-host trace "
                    "(spans assembled by the master from every node)")
    ap.add_argument("--master", default="localhost:5000",
                    help="master address host:port (default %(default)s)")
    ap.add_argument("--bulk", type=int, default=None,
                    help="bulk id (default: the active/most recent bulk)")
    ap.add_argument("-o", "--out", default=None,
                    help="write the merged Perfetto/Chrome JSON here")
    ap.add_argument("--top", type=int, default=10,
                    help="straggler rows to print (default %(default)s)")
    ap.add_argument("--verify", action="store_true",
                    help="audit chain completeness: every task span must "
                         "chain unbroken to the root with stage + op "
                         "children (exit 1 on breaks)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (straggler summary / "
                         "verify report)")
    ap.add_argument("--raw-clocks", action="store_true",
                    help="keep each host's uncorrected timestamps "
                         "instead of rebasing remote spans onto master "
                         "time via the per-node clock offsets")
    args = ap.parse_args(argv)

    from scanner_tpu.engine.rpc import RpcClient
    from scanner_tpu.engine.service import MASTER_SERVICE
    from scanner_tpu.util import tracing

    client = RpcClient(args.master, MASTER_SERVICE, timeout=30.0)
    try:
        reply = client.try_call("GetTrace", bulk_id=args.bulk,
                                raw_clocks=args.raw_clocks, retries=1)
    finally:
        client.close()
    if reply is None:
        print(f"scanner-trace: master {args.master} unreachable",
              file=sys.stderr)
        return 2
    if "spans" not in reply:
        print(f"scanner-trace: {reply.get('error', 'no trace')}",
              file=sys.stderr)
        return 2
    spans = reply["spans"]
    if args.out:
        tracing.write_chrome_trace(spans, args.out)
        clocks = "raw clocks" if args.raw_clocks else (
            "clock-rebased" if reply.get("clock_rebased")
            else "no clock correction")
        print(f"scanner-trace: wrote {len(spans)} spans to {args.out} "
              f"({clocks})", file=sys.stderr)
    if args.verify:
        report = tracing.verify_chain(spans)
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(f"tasks={report['tasks']} "
                  f"trace_ids={len(report['trace_ids'])} "
                  f"complete={report['complete']}")
            for b in report["broken"][:20]:
                print(f"  BROKEN: {b}")
        return 0 if report["complete"] else 1
    if not args.out or args.json:
        # recompute from the full dump (same shape the master maintains
        # incrementally) so --top honors the requested N
        summary = tracing.straggler_summary(spans, top_n=args.top)
        summary["spans"] = len(spans)
        summary["spans_dropped"] = reply.get("spans_dropped", 0)
        if args.json:
            print(json.dumps(summary, indent=1))
        else:
            print(_fmt_stragglers(reply.get("trace_id", "?"), summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
