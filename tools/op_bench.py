"""Per-op device/host A/B bench (the reference DeviceTestBench analog,
py_test.py:438 — CPU-vs-GPU benches of the same op).

For every hot op (kernel stdlib + model zoo inference) this tool runs the
same computation on the host CPU backend and on the accelerator, checks
the results agree, and reports throughput for both.  Forced completion:
every timed repetition device_gets a scalar that depends on the result —
`block_until_ready` can return early over the tunnel and inflate numbers
~1000x (PERF.md §2 pitfall).

Run: python tools/op_bench.py [--reps N]
Output: one JSON line per op to stdout + OP_BENCH.json at the repo root;
on a host with no reachable accelerator the device columns are absent
(the tool still validates and times the host paths).
tools/tpu_window.py runs this on every healthy tunnel window.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "OP_BENCH.json")

BATCH, H, W = 16, 480, 640

# op name -> ABSOLUTE max_abs_diff allowed for host/device agreement.
# Histograms are integer counts (bit exact); resize/blur are uint8 with
# f32-vs-bf16 interpolation, so one rounding count of slack.  Model
# inference rows get no verdict: trained nets on random-noise frames have
# near-tied argmaxes/scores, so cross-backend diffs are expected — the
# tool records max_abs_diff as information only (engine-level model
# equivalence is pinned by the test suite on real scene fixtures).
ATOL = {
    "histogram_cmp": 0.0,
    "histogram_pallas": 0.0,
    "resize_320x240": 1.0,
    "blur": 1.0,
    # integer fixed-point conversion: bit-exact across backends
    "yuv420_to_rgb": 0.0,
}
# device-only ops validated against a host op with identical semantics
REF_OP = {"histogram_pallas": "histogram_cmp"}


def _force(x) -> float:
    """Materialize a scalar that depends on every result element."""
    import jax
    import jax.numpy as jnp
    leaves = [jnp.asarray(l) for l in jax.tree_util.tree_leaves(x)]
    return float(jax.device_get(sum(jnp.sum(l.astype(jnp.float32))
                                    for l in leaves)))


def _bench(fn, batch, reps) -> float:
    """Frames/sec over `reps` forced repetitions (first call warms jit)."""
    import jax
    import jax.numpy as jnp
    _force(fn(batch))
    t0 = time.time()
    acc = None
    for _ in range(reps):
        r = fn(batch)
        s = sum(jnp.sum(jnp.asarray(l).astype(jnp.float32))
                for l in jax.tree_util.tree_leaves(r))
        acc = s if acc is None else acc + s
    _ = float(jax.device_get(acc))
    return BATCH * reps / (time.time() - t0)


def _make_cases(dev):
    """(name, fn) pairs built for `dev` (the active default device), so
    model params live where the computation runs.  fn maps a resident
    (B, H, W, 3) uint8 batch to a pytree of arrays."""
    from scanner_tpu.common import DeviceType
    from scanner_tpu.graph.ops import KernelConfig, registry
    import scanner_tpu.models  # noqa: F401  (registers model ops)
    import scanner_tpu.kernels  # noqa: F401
    from scanner_tpu.kernels.imgproc import (_blur_impl,
                                             _gaussian_kernel1d,
                                             _histogram_cmp_impl,
                                             _resize_impl)

    cfg = KernelConfig(device=DeviceType.TPU, devices=[dev])

    def model(name, **kw):
        kern = registry.get(name).kernel_factory(cfg, **kw)
        return lambda b: kern.execute(b)

    import jax.numpy as jnp
    gk = jnp.asarray(_gaussian_kernel1d(5, 1.5))
    cases = [
        ("histogram_cmp", lambda b: _histogram_cmp_impl(b)),
        ("resize_320x240", lambda b: _resize_impl(b, 240, 320)),
        ("blur", lambda b: _blur_impl(b.astype(jnp.float32), gk, 5)),
        ("pose_infer_w8", model("PoseDetect", width=8)),
        ("objdet_infer_w8", model("ObjectDetect", width=8)),
        ("seg_infer_w8", model("InstanceSegment", width=8)),
        ("embed_infer_w8", model("FaceEmbedding", width=8)),
    ]
    if dev.platform == "tpu":
        from scanner_tpu.kernels.pallas_ops import histogram_frames
        cases.insert(1, ("histogram_pallas",
                         lambda b: histogram_frames(b)))

    # the YUV420-wire on-device conversion (kernels/color.py): input is
    # flat I420 rows rather than the shared RGB batch — built lazily on
    # the active device, same bytes both backends (agreement bit-exact)
    def yuv_case():
        import jax

        from scanner_tpu.kernels.color import yuv420_to_rgb_device
        from scanner_tpu.video.lib import yuv420_frame_bytes
        state = {}

        def fn(_b):
            if "flat" not in state:
                r = np.random.RandomState(1)
                state["flat"] = jax.device_put(r.randint(
                    0, 256, (BATCH, yuv420_frame_bytes(H, W)), np.uint8))
            return yuv420_to_rgb_device(state["flat"], H, W)

        return fn

    cases.append(("yuv420_to_rgb", yuv_case()))
    return cases


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--reps", type=int, default=10)
    args = ap.parse_args()

    import jax

    accel = next((d for d in jax.devices() if d.platform != "cpu"), None)
    try:
        cpu = jax.devices("cpu")[0]
    except Exception:
        cpu = None

    rng = np.random.RandomState(0)
    host_batch = rng.randint(0, 255, (BATCH, H, W, 3), dtype=np.uint8)
    rows = {}
    for label, dev in (("host", cpu), ("device", accel)):
        if dev is None:
            continue
        with jax.default_device(dev):
            for name, fn in _make_cases(dev):
                row = rows.setdefault(name, {"op": name})
                try:
                    batch = jax.device_put(host_batch, dev)
                    row[f"{label}_fps"] = round(
                        _bench(fn, batch, args.reps), 1)
                    row[f"_{label}_out"] = jax.device_get(fn(batch))
                except Exception as e:  # noqa: BLE001
                    row[f"{label}_error"] = \
                        f"{type(e).__name__}: {str(e)[:160]}"

    host_outs = {name: row.get("_host_out") for name, row in rows.items()}
    for name, row in rows.items():
        ref = row.pop("_host_out", None)
        if ref is None and name in REF_OP:
            # device-only lowering: validate against the host op with the
            # same output contract
            ref = host_outs.get(REF_OP[name])
            row["reference_op"] = REF_OP[name]
        got = row.pop("_device_out", None)
        if ref is not None and got is not None:
            import jax
            ref_leaves = jax.tree_util.tree_leaves(ref)
            got_leaves = jax.tree_util.tree_leaves(got)
            if len(ref_leaves) != len(got_leaves):
                # zip() would truncate and silently under-report the diff
                row["max_abs_diff"] = (
                    f"STRUCTURE MISMATCH: {len(ref_leaves)} host leaves "
                    f"vs {len(got_leaves)} device leaves")
                if name in ATOL:
                    row["agrees"] = False
            else:
                shapes = [(np.shape(a), np.shape(b))
                          for a, b in zip(ref_leaves, got_leaves)]
                bad = [s for s in shapes if s[0] != s[1]]
                if bad:
                    row["max_abs_diff"] = (
                        f"SHAPE MISMATCH: host {bad[0][0]} vs device "
                        f"{bad[0][1]}")
                    if name in ATOL:
                        row["agrees"] = False
                else:
                    diffs = [float(np.abs(np.asarray(a, np.float32) -
                                          np.asarray(b, np.float32)).max())
                             for a, b in zip(ref_leaves, got_leaves)]
                    row["max_abs_diff"] = max(diffs) if diffs else 0.0
                    if name in ATOL:
                        row["agrees"] = bool(
                            row["max_abs_diff"] <= ATOL[name])
        if "host_fps" in row and "device_fps" in row:
            row["speedup"] = round(
                row["device_fps"] / max(row["host_fps"], 1e-9), 1)
        print(json.dumps(row), flush=True)

    result = {"batch": [BATCH, H, W, 3], "reps": args.reps,
              "clock": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "ops": list(rows.values())}
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
