#!/usr/bin/env python3
"""scanner-model CLI: exhaustively explore the control-plane protocol.

    python tools/scanner_model.py                      # every scenario
    python tools/scanner_model.py --scenario failover
    python tools/scanner_model.py --scenario crash --broken ack_before_commit
    python tools/scanner_model.py --json

Explores every interleaving of the abstract Master/Worker/Journal
state machine (scanner_tpu/analysis/model/) up to a depth bound and
asserts the durability/fencing invariants at every reachable state.
Exit 1 with a minimal counterexample schedule on violation, exit 2 if
a bound truncated the exploration (widen --depth / --max-states).
`--broken` injects a known defect; the explorer is expected to find
it — used by tests/test_scanner_model.py to prove the checker has
teeth.  See docs/static-analysis.md (scanner-model section).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from scanner_tpu.analysis.model import (  # noqa: E402
    DEFAULT_DEPTH, DEFAULT_MAX_STATES, SCENARIOS, explore_scenario)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="scanner-model",
        description="bounded-interleaving checker for the scanner_tpu "
                    "control plane (docs/static-analysis.md)")
    ap.add_argument("--scenario", choices=sorted(SCENARIOS),
                    action="append", default=None,
                    help="scenario(s) to explore (default: all)")
    ap.add_argument("--broken",
                    choices=("ack_before_commit", "skip_dedup",
                             "ignore_fence"),
                    default=None,
                    help="inject a known defect — the explorer must "
                         "find it")
    ap.add_argument("--depth", type=int, default=DEFAULT_DEPTH,
                    help=f"schedule depth bound (default "
                         f"{DEFAULT_DEPTH})")
    ap.add_argument("--max-states", type=int,
                    default=DEFAULT_MAX_STATES,
                    help=f"state-count bound (default "
                         f"{DEFAULT_MAX_STATES})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    names = args.scenario or sorted(SCENARIOS)
    reports = [explore_scenario(n, args.broken, depth=args.depth,
                                max_states=args.max_states)
               for n in names]

    if args.as_json:
        print(json.dumps([r.to_dict() for r in reports], indent=1))
    else:
        for r in reports:
            tag = "BROKEN " + r.broken if r.broken else "ok"
            bound = "exhausted" if r.exhausted else "TRUNCATED"
            print(f"[{r.scenario}] {r.states} states, {r.edges} edges, "
                  f"{r.schedules} interleavings, depth "
                  f"{r.max_depth_seen} ({bound}) [{tag}]")
            if r.violation is not None:
                print(r.violation.format())

    if any(not r.ok for r in reports):
        return 1
    if any(not r.exhausted for r in reports):
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
