"""Opportunistic TPU benchmark capture.

The accelerator tunnel on this host is intermittently healthy; waiting
until end-of-round to benchmark risks recording a CPU fallback (rounds 1-2
both did).  This tool probes the tunnel cheaply and, when healthy, runs
the full bench + device microbenchmarks immediately, archiving results to
``BENCH_TPU_CAPTURE.json`` at the repo root.  ``bench.py`` reports the
archived hardware numbers (clearly labeled) whenever the tunnel is down
at bench time.

Run it on a schedule during the round: ``python tools/tpu_capture.py``.
Exit codes: 0 captured (or fresh capture already present), 2 tunnel down,
3 bench failed.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPTURE = os.path.join(REPO, "BENCH_TPU_CAPTURE.json")
PROBE_TIMEOUT = float(os.environ.get("TPU_PROBE_TIMEOUT", "90"))
BENCH_TIMEOUT = float(os.environ.get("TPU_BENCH_TIMEOUT", "2400"))

_MICROBENCH = r"""
import json, time, sys
import numpy as np
import jax, jax.numpy as jnp
dev = jax.devices()[0]
out = {"device": str(dev), "platform": dev.platform}
f = jax.jit(lambda x: x + 1)
x = jnp.zeros((8,), jnp.float32)
f(x).block_until_ready()
t0 = time.time()
for _ in range(20):
    f(x).block_until_ready()
out["dispatch_ms"] = round((time.time() - t0) / 20 * 1000, 3)
a = np.random.randint(0, 255, size=(64, 1024, 1024), dtype=np.uint8)
tot = jax.jit(lambda v: jnp.sum(v, dtype=jnp.int32))
d = jax.device_put(a, dev); _ = jax.device_get(tot(d))
# per-rep overhead baseline (dispatch + reduce-of-resident + scalar RTT)
# so the forced-completion loop below charges only the copy itself
t0 = time.time()
for _ in range(3):
    _ = jax.device_get(tot(d))
base_s = (time.time() - t0) / 3
t0 = time.time()
for _ in range(3):
    d = jax.device_put(a, dev); _ = jax.device_get(tot(d))
copy_s = max((time.time() - t0) / 3 - base_s, 1e-9)
out["h2d_MBps"] = round(a.nbytes / copy_s / 1e6, 1)
t0 = time.time()
for _ in range(3):
    _ = jax.device_get(d)
out["d2h_MBps"] = round(a.nbytes / ((time.time() - t0) / 3) / 1e6, 1)
m = jnp.ones((4096, 4096), jnp.bfloat16)
mm = jax.jit(lambda p, q: p @ q)
# NOTE: block_until_ready over the axon tunnel can return before the
# computation completes; force completion by fetching a dependent scalar
_ = jax.device_get(jnp.sum(mm(m, m).astype(jnp.float32)))
t0 = time.time()
r = m
for _ in range(10):
    r = mm(r, m)
_ = jax.device_get(jnp.sum(r.astype(jnp.float32)))
out["matmul_TFLOPs"] = round(10 * 2 * 4096**3 / (time.time() - t0) / 1e12, 2)
print(json.dumps(out))
"""


def tunnel_up() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices()[0]; assert d.platform=='tpu'"],
            cwd=REPO, timeout=PROBE_TIMEOUT,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return r.returncode == 0
    except Exception:
        return False


def run_microbench():
    try:
        r = subprocess.run([sys.executable, "-c", _MICROBENCH], cwd=REPO,
                           timeout=600, capture_output=True, text=True)
        if r.returncode == 0:
            return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception:
        pass
    return None


def run_bench():
    env = dict(os.environ, BENCH_CONFIGS="all")
    r = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                       timeout=BENCH_TIMEOUT, capture_output=True,
                       text=True, env=env)
    if r.returncode != 0:
        print(f"bench failed rc={r.returncode}:\n{r.stderr[-2000:]}",
              file=sys.stderr)
        return None, None
    headline = json.loads(r.stdout.strip().splitlines()[-1])
    detail_path = os.path.join(REPO, "BENCH_DETAIL.json")
    detail = json.load(open(detail_path)) if os.path.exists(detail_path) \
        else []
    return headline, detail


def main() -> int:
    force = "--force" in sys.argv
    if os.path.exists(CAPTURE) and not force:
        age_h = (time.time() - os.path.getmtime(CAPTURE)) / 3600
        prev = json.load(open(CAPTURE))
        if prev.get("detail") and age_h < 6:
            print(f"capture already present ({age_h:.1f}h old); "
                  "use --force to redo")
            return 0
    if not tunnel_up():
        print("tunnel down")
        return 2
    print("tunnel healthy; running microbench + full bench", flush=True)
    micro = run_microbench()
    headline, detail = run_bench()
    if headline is None or not any(
            d.get("platform") == "tpu" for d in detail or []):
        print("bench did not produce TPU numbers")
        return 3
    best = {
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "source": "opportunistic_capture",
        "headline": headline,
        "detail": detail,
        "microbench": micro,
    }
    # keep the better capture (mean headline value) if one exists
    if os.path.exists(CAPTURE):
        try:
            prev = json.load(open(CAPTURE))
            if prev.get("headline", {}).get("value", 0) > \
                    headline.get("value", 0):
                print("previous capture was better; keeping it")
                return 0
        except Exception:
            pass
    with open(CAPTURE, "w") as f:
        json.dump(best, f, indent=1)
    print(f"captured: {json.dumps(headline)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
