"""scanner-cost: compute-efficiency report for a scanner_tpu cluster.

The reading half of the efficiency plane (scanner_tpu/util/coststats.py,
docs/observability.md §Efficiency & Compilation): dials the master's
GetCompileLedger RPC and renders, per node,

  * the roofline table — achieved FLOP/s / bytes/s, the
    compute-vs-memory-bound verdict and EFF% per (op, device, bucket);
  * the XLA compile ledger — what actually compiled, how long it took,
    whether the persistent cache hit, and the executable/analytical
    cost XLA reported.

    python tools/scanner_cost.py --master localhost:5000
    python tools/scanner_cost.py --master localhost:5000 --ledger 20
    python tools/scanner_cost.py --master localhost:5000 --json
    python tools/scanner_cost.py --detail BENCH_DETAIL.json   # offline

Exit codes: 0 ok, 2 master unreachable / detail file unreadable.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fmt_rate(v: float) -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if v >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def render_ops(node: str, ops) -> list:
    lines = []
    if not ops:
        return lines
    # fused chain ids ("Resize+Blur+Histogram") exceed the classic
    # 16-char op column: widen to the longest label on screen
    w = max(16, max(len(o["op"]) for o in ops))
    lines.append(f"{'OP':{w}} {'DEVICE':>9} {'BUCKET':>6} {'CALLS':>6} "
                 f"{'EFF%':>7} {'BOUND':>8} {'FLOP/s':>9} {'B/s':>9} "
                 f"{'SRC':>8}")
    for o in ops:
        lines.append(
            f"{o['op']:{w}} {o['device']:>9} {o['bucket']:>6} "
            f"{o['calls']:>6} {o['efficiency'] * 100:>6.1f}% "
            f"{o['bound']:>8} {_fmt_rate(o['flops_per_s']):>9} "
            f"{_fmt_rate(o['bytes_per_s']):>9} "
            f"{o.get('cost_source', '?'):>8}")
    return lines


def render_ledger(entries, n: int) -> list:
    lines = []
    if not entries:
        return lines
    shown = entries[-n:]
    w = max(16, max(len(e["op"]) for e in shown))
    lines.append(f"{'OP':{w}} {'DEVICE':>9} {'BUCKET':>6} {'CACHE':>8} "
                 f"{'SECONDS':>8} {'EXEC B':>9} {'FLOPS':>9} {'TASK':>8}")
    for e in shown:
        lines.append(
            f"{e['op']:{w}} {e['device']:>9} {e['bucket']:>6} "
            f"{e['cache']:>8} {e['compile_s']:>8.4f} "
            f"{e.get('exec_bytes') or 0:>9} "
            f"{_fmt_rate(e['flops']) if e.get('flops') else '-':>9} "
            f"{str(e.get('task') or '-'):>8}")
    return lines


def render(nodes: dict, ledger_n: int) -> str:
    lines = []
    for node in sorted(nodes):
        rep = nodes[node] or {}
        summ = rep.get("summary") or {}
        hr = summ.get("cache_hit_rate")
        lines.append(
            f"== {node}: {summ.get('compiles', 0)} compiles in "
            f"{summ.get('compile_seconds', 0.0)}s "
            f"({summ.get('entries', 0)} ledger entries"
            + (f", {summ.get('entries_seen', 0)} seen" if
               summ.get("entries_seen", 0) != summ.get("entries", 0)
               else "")
            + "), cache hit rate "
            + (f"{hr:.0%}" if hr is not None else "n/a (no cache)"))
        ops = render_ops(node, rep.get("op_efficiency") or [])
        if ops:
            lines.append("")
            lines.extend(ops)
        led = render_ledger(rep.get("ledger") or [], ledger_n)
        if led:
            lines.append("")
            lines.extend(led)
        lines.append("")
    return "\n".join(lines).rstrip() or "no efficiency data recorded"


def detail_nodes(path: str):
    """Offline mode: reshape a BENCH_DETAIL.json op_efficiency digest
    into the per-node report shape the renderer expects."""
    with open(path) as f:
        detail = json.load(f)
    for d in detail if isinstance(detail, list) else []:
        if isinstance(d, dict) and d.get("config") == "op_efficiency":
            return {"bench": {"summary": d.get("compile") or {},
                              "op_efficiency": d.get("ops") or [],
                              "ledger": []}}
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-op roofline efficiency + XLA compile ledger "
                    "for a scanner_tpu cluster")
    ap.add_argument("--master", default=None,
                    help="master address host:port")
    ap.add_argument("--detail", default=None,
                    help="offline: read a BENCH_DETAIL.json "
                         "op_efficiency digest instead of a cluster")
    ap.add_argument("--ledger", type=int, default=10,
                    help="newest compile-ledger entries to show per "
                         "node (default %(default)s)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.detail:
        try:
            nodes = detail_nodes(args.detail)
        except (OSError, ValueError) as e:
            print(f"scanner-cost: cannot read {args.detail}: {e}",
                  file=sys.stderr)
            return 2
        if nodes is None:
            print(f"scanner-cost: no op_efficiency digest in "
                  f"{args.detail}", file=sys.stderr)
            return 2
    else:
        from scanner_tpu.engine.rpc import RpcClient
        from scanner_tpu.engine.service import MASTER_SERVICE

        master = args.master or "localhost:5000"
        client = RpcClient(master, MASTER_SERVICE, timeout=10.0)
        try:
            reply = client.try_call("GetCompileLedger", retries=1)
        finally:
            client.close()
        if reply is None or "nodes" not in reply:
            print(f"scanner-cost: master {master} unreachable",
                  file=sys.stderr)
            return 2
        nodes = reply["nodes"]

    if args.json:
        print(json.dumps({"nodes": nodes}, indent=1, default=str))
    else:
        print(render(nodes, args.ledger))
    return 0


if __name__ == "__main__":
    sys.exit(main())
