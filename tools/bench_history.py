"""bench-history: read the banked perf trajectory and flag regressions.

Every bench round writes BENCH_r<NN>.json (the headline metric) and the
latest round's BENCH_DETAIL.json (per-config digests: fps, task-latency
quantiles, the health/alerts digest).  Until now that trajectory was
unread by anything — a regression was invisible until a human diffed
the files by hand.  This tool closes the loop:

    python tools/bench_history.py                      # repo-root files
    python tools/bench_history.py --dir /path --json   # machine-readable
    python tools/bench_history.py --threshold 0.10     # stricter gate
    python tools/bench_history.py --all                # every consecutive
                                                       # pair, not just the
                                                       # newest

Per metric, prints the per-round history and compares the NEWEST point
against the previous point of the same metric (capture-source changes
and metric renames start a fresh series, so an infra swap doesn't read
as a code regression).  A drop beyond --threshold exits 1 — the CI
hook: `bench_history.py || echo PERF REGRESSION`.  Exit codes: 0 ok,
1 regression, 2 no bench files found.
"""

import argparse
import glob
import json
import os
import re
import sys
import time

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# replayed-capture staleness guard: a round whose headline is an
# opportunistic hardware capture replay (bench.py _report_capture,
# source="opportunistic_capture") is only as fresh as the capture it
# replays — past this age the "hardware trajectory" is a fossil and the
# tool says so loudly instead of letting it pass as current data.
DEFAULT_MAX_CAPTURE_AGE_DAYS = 14.0


def capture_staleness(bench_dir, rounds, max_age_days, now=None):
    """{} unless the NEWEST round replays an opportunistic hardware
    capture; otherwise {"captured_at", "age_days", "stale"} — stale
    when the capture is older than `max_age_days` (or undatable)."""
    if not rounds:
        return {}
    newest = rounds[-1][1]
    if newest.get("source") != "opportunistic_capture":
        return {}
    stamp = newest.get("captured_at")
    if not stamp:
        # older rounds didn't echo captured_at into the headline; fall
        # back to the capture file itself
        try:
            with open(os.path.join(bench_dir,
                                   "BENCH_TPU_CAPTURE.json")) as f:
                stamp = json.load(f).get("captured_at")
        except (OSError, ValueError):
            stamp = None
    age_days = None
    if stamp:
        try:
            tm = time.strptime(str(stamp), "%Y-%m-%dT%H:%M:%S")
            age_days = round(
                ((now if now is not None else time.time())
                 - time.mktime(tm)) / 86400.0, 2)
        except (ValueError, OverflowError):
            age_days = None
    # an undatable capture counts as stale: "can't tell how old" must
    # not read as "fresh"
    return {"captured_at": stamp, "age_days": age_days,
            "max_age_days": max_age_days,
            "stale": age_days is None or age_days > max_age_days}


def load_rounds(bench_dir):
    """[(round, parsed-dict)] sorted by round, skipping unreadable or
    metric-less files (a failed round writes rc!=0 and no `parsed`)."""
    out = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed \
                and "value" in parsed:
            out.append((int(m.group(1)), parsed))
    out.sort(key=lambda t: t[0])
    return out


def series_by_metric(rounds):
    """{metric: [(round, value, source)]} preserving round order."""
    by = {}
    for rnd, p in rounds:
        by.setdefault(p["metric"], []).append(
            (rnd, float(p["value"]), p.get("source", "")))
    return by


def find_regressions(by_metric, threshold, check_all=False):
    """[(metric, prev_round, prev, cur_round, cur, drop_frac)] for
    same-source consecutive drops beyond `threshold`.  Default checks
    only the newest pair per metric (the CI question is "did the last
    round regress", not "did history ever dip"); --all audits every
    consecutive pair."""
    regs = []
    for metric, pts in by_metric.items():
        pairs = zip(pts, pts[1:]) if check_all \
            else (zip(pts[-2:], pts[-1:]) if len(pts) >= 2 else ())
        for (r0, v0, s0), (r1, v1, s1) in pairs:
            if s0 != s1:
                # a capture-source change (live TPU -> replayed capture)
                # resets the baseline: not a code regression
                continue
            if v0 > 0 and (v0 - v1) / v0 > threshold:
                regs.append((metric, r0, v0, r1, v1, (v0 - v1) / v0))
    return regs


def detail_digest(bench_dir):
    """The latest round's BENCH_DETAIL.json, reduced to the lines a
    trajectory reader wants: per-config fps, task-latency quantiles,
    the health/alerts digest, the per-op efficiency table and the
    stable baseline metrics.  {} when the file is absent."""
    path = os.path.join(bench_dir, "BENCH_DETAIL.json")
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            detail = json.load(f)
    except (OSError, ValueError):
        return {}
    out = {"fps_by_config": {}, "task_latency": {}, "health": {},
           "op_efficiency": {}, "frame_cache": {}, "remediation": {},
           "failover": {}, "gang_skew": {}, "gang_sharded": {},
           "baseline_metrics": {}}
    for d in detail:
        if not isinstance(d, dict):
            continue
        if "fps" in d:
            out["fps_by_config"][str(d.get("config"))] = d["fps"]
        elif d.get("config") == "task_latency":
            out["task_latency"] = {k: v for k, v in d.items()
                                   if k != "config"}
        elif d.get("config") == "health":
            out["health"] = {k: v for k, v in d.items()
                            if k not in ("config", "rpc_latency")}
        elif d.get("config") in ("op_efficiency", "op_efficiency_hw"):
            out["op_efficiency"][d["config"]] = {
                k: v for k, v in d.items() if k != "config"}
        elif d.get("config") in ("frame_cache", "frame_cache_hw"):
            out["frame_cache"][d["config"]] = {
                k: v for k, v in d.items() if k != "config"}
        elif d.get("config") == "remediation":
            out["remediation"] = {k: v for k, v in d.items()
                                  if k != "config"}
        elif d.get("config") == "failover":
            out["failover"] = {k: v for k, v in d.items()
                               if k != "config"}
        elif d.get("config") in ("gang_skew", "gang_skew_hw"):
            out["gang_skew"][d["config"]] = {
                k: v for k, v in d.items() if k != "config"}
        elif d.get("config") in ("gang_sharded", "gang_sharded_hw"):
            out["gang_sharded"][d["config"]] = {
                k: v for k, v in d.items() if k != "config"}
        elif d.get("config") == "baseline_metrics":
            out["baseline_metrics"] = d.get("metrics") or {}
    return out


# stable per-direction baseline gate: bench.py banks `baseline_metrics`
# (each with a declared better= direction) into BENCH_DETAIL.json;
# --write-baselines snapshots them here, and every later run compares
# against the snapshot so the serving/cache/kernel directions gate the
# moment their first healthy round banks a baseline.
BASELINES_FILE = "BENCH_BASELINES.json"


def load_baselines(bench_dir):
    path = os.path.join(bench_dir, BASELINES_FILE)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc.get("metrics", {}) if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def write_baselines(bench_dir, metrics):
    path = os.path.join(bench_dir, BASELINES_FILE)
    known = {k: v for k, v in metrics.items()
             if isinstance(v, dict) and v.get("value") is not None}
    with open(path, "w") as f:
        json.dump({"metrics": known}, f, indent=1)
    return path


def find_detail_regressions(baselines, current, threshold):
    """[(metric, baseline, now, change_frac)] where a baseline-metrics
    value moved against its declared direction beyond `threshold`.
    Metrics absent from either side (no baseline banked yet, or not
    measurable this round) are skipped — a CPU-fallback round must not
    page on a missing hardware number."""
    regs = []
    for name, base in baselines.items():
        cur = current.get(name)
        if not isinstance(base, dict) or not isinstance(cur, dict):
            continue
        b, c = base.get("value"), cur.get("value")
        if b is None or c is None or not b:
            continue
        better = base.get("better", cur.get("better", "higher"))
        change = (c - b) / abs(b)
        if better == "lower":
            change = -change
        # change is now "improvement fraction": negative = worse
        if change < -threshold:
            regs.append((name, b, c, change))
    return regs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="print the BENCH_r*.json perf trajectory and flag "
                    "regressions (exit 1)")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional drop that counts as a regression "
                         "(default %(default)s)")
    ap.add_argument("--all", action="store_true",
                    help="check every consecutive same-source pair, "
                         "not just the newest")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--max-capture-age-days", type=float,
                    default=DEFAULT_MAX_CAPTURE_AGE_DAYS,
                    help="when the newest round replays a hardware "
                         "capture (source=opportunistic_capture), "
                         "captures older than this print a STALE "
                         "CAPTURE banner (default %(default)s)")
    ap.add_argument("--write-baselines", action="store_true",
                    help="snapshot the latest BENCH_DETAIL "
                         "baseline_metrics into BENCH_BASELINES.json — "
                         "the per-direction gate (task-latency p99, "
                         "per-op efficiency, compile-cache hit rate) "
                         "compares every later run against it")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"bench-history: no BENCH_r*.json under {args.dir}",
              file=sys.stderr)
        return 2
    by_metric = series_by_metric(rounds)
    regs = find_regressions(by_metric, args.threshold, args.all)
    detail = detail_digest(args.dir)
    base_metrics = detail.get("baseline_metrics") or {}
    if args.write_baselines and base_metrics:
        path = write_baselines(args.dir, base_metrics)
        print(f"bench-history: baselines written to {path}",
              file=sys.stderr)
    detail_regs = find_detail_regressions(
        load_baselines(args.dir), base_metrics, args.threshold)
    stale = capture_staleness(args.dir, rounds,
                              args.max_capture_age_days)

    if args.json:
        print(json.dumps({
            "rounds": [r for r, _p in rounds],
            "metrics": {m: [{"round": r, "value": v, "source": s}
                            for r, v, s in pts]
                        for m, pts in by_metric.items()},
            "regressions": [
                {"metric": m, "from_round": r0, "from": v0,
                 "to_round": r1, "to": v1, "drop": round(drop, 4)}
                for m, r0, v0, r1, v1, drop in regs],
            "detail_regressions": [
                {"metric": m, "baseline": b, "value": c,
                 "change": round(ch, 4)}
                for m, b, c, ch in detail_regs],
            "threshold": args.threshold,
            "stale_capture": stale,
            "detail": detail,
        }, indent=1))
        return 1 if regs or detail_regs else 0

    if stale.get("stale"):
        age = stale.get("age_days")
        print("=" * 64)
        print(f"  STALE CAPTURE: newest round replays a hardware "
              f"capture from {stale.get('captured_at') or 'unknown'}"
              + (f" ({age} days old" if age is not None
                 else " (age unknown")
              + f" > --max-capture-age-days "
                f"{args.max_capture_age_days:g}).")
        print("  The hardware trajectory below is NOT current data — "
              "re-run bench.py with the")
        print("  TPU tunnel up (tools/tpu_window.py) to bank a fresh "
              "capture.")
        print("=" * 64)
    print(f"bench-history: {len(rounds)} rounds "
          f"(r{rounds[0][0]:02d}..r{rounds[-1][0]:02d}), "
          f"threshold {args.threshold:.0%}")
    for metric, pts in sorted(by_metric.items()):
        print(f"\n{metric}")
        prev = None
        for rnd, v, src in pts:
            delta = ""
            if prev is not None and prev > 0:
                delta = f"  {((v - prev) / prev):+7.1%}"
            tag = f"  [{src}]" if src else ""
            print(f"  r{rnd:02d}  {v:10.2f}{delta}{tag}")
            prev = v
    if detail:
        print("\nlatest BENCH_DETAIL digest:")
        for cfg, fps in sorted(detail.get("fps_by_config", {}).items()):
            print(f"  config {cfg}: {fps} fps")
        tl = detail.get("task_latency") or {}
        if tl:
            print("  task latency: " + "  ".join(
                f"{k}={v}" for k, v in sorted(tl.items())))
        h = detail.get("health") or {}
        if h:
            trans = h.get("alert_transitions") or {}
            fired = sum(v for k, v in trans.items()
                        if k.endswith(":firing"))
            print(f"  health: {h.get('status', '?')} "
                  f"({int(fired)} alert firings during the run)")
        eff = (detail.get("op_efficiency") or {}).get("op_efficiency")
        if eff and eff.get("ops"):
            for o in eff["ops"][:8]:
                print(f"  eff {o['op']}@{o['device']} b{o['bucket']}: "
                      f"{o['efficiency']:.2%} ({o['bound']}-bound)")
            comp = eff.get("compile") or {}
            hr = comp.get("cache_hit_rate")
            print(f"  compile: {comp.get('compiles', 0)} in "
                  f"{comp.get('compile_seconds', 0)}s, cache hit rate "
                  + (f"{hr:.0%}" if hr is not None else "n/a"))
        fcd = (detail.get("frame_cache") or {}).get("frame_cache")
        if fcd and fcd.get("enabled"):
            hr = fcd.get("hit_rate")
            print(f"  frame cache: hit rate "
                  + (f"{hr:.0%}" if hr is not None else "n/a")
                  + f", decode saved {fcd.get('decode_seconds_saved')}s"
                  f", h2d saved "
                  f"{(fcd.get('h2d_bytes_saved') or 0) / 1e6:.1f} MB")
        rem = detail.get("remediation") or {}
        if rem.get("enabled"):
            n_applied = sum(
                v for k, v in (rem.get("remediations") or {}).items()
                if "applied" in k)
            print(f"  remediation: preemption recovery "
                  f"{rem.get('preemption_recovery_s')}s, "
                  f"{int(rem.get('preemptions') or 0)} preemption(s), "
                  f"strikes {int(rem.get('strike_delta') or 0)}, "
                  f"{int(n_applied)} action(s) applied")
        fo = detail.get("failover") or {}
        if fo.get("rows_ok"):
            print(f"  failover: recovery "
                  f"{fo.get('failover_recovery_s')}s, "
                  f"{int(fo.get('tasks_lost_on_recovery') or 0)} "
                  f"task(s) lost, "
                  f"{int(fo.get('journal_replayed') or 0)} journal "
                  f"record(s) replayed")
        for cfg, gs in sorted(
                (detail.get("gang_skew") or {}).items()):
            p99 = gs.get("gang_barrier_skew_p99_s")
            unc = gs.get("clock_offset_uncertainty_s")
            print(f"  {cfg}: barrier skew p99 "
                  + (f"{p99 * 1e3:.1f}ms" if p99 is not None
                     else "n/a")
                  + ", clock uncertainty "
                  + (f"{unc * 1e3:.1f}ms" if unc is not None
                     else "n/a")
                  + f", {int(gs.get('skews_observed') or 0)} "
                    f"epoch(s) observed")
        if base_metrics:
            print("  baselines: " + "  ".join(
                f"{k}={v.get('value')}" for k, v in
                sorted(base_metrics.items())
                if isinstance(v, dict)))
    if regs or detail_regs:
        print("\nREGRESSIONS:")
        for m, r0, v0, r1, v1, drop in regs:
            print(f"  {m}: r{r0:02d} {v0:.2f} -> r{r1:02d} {v1:.2f} "
                  f"({drop:.1%} drop > {args.threshold:.0%})")
        for m, b, c, ch in detail_regs:
            print(f"  {m}: baseline {b} -> {c} "
                  f"({-ch:.1%} worse > {args.threshold:.0%})")
        return 1
    print("\nno regressions beyond threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
