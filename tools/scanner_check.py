#!/usr/bin/env python3
"""Repo-native static analysis CLI (thin wrapper).

    python tools/scanner_check.py scanner_tpu/
    python tools/scanner_check.py --json
    python tools/scanner_check.py --list-codes

The implementation lives in scanner_tpu/analysis/static/ (the
`scanner-check` console script points there too); this wrapper only
makes the repo checkout importable when invoked directly.  See
docs/static-analysis.md.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from scanner_tpu.analysis.static.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
