#!/bin/bash
# Probe the accelerator tunnel every ~10 min; on the first healthy window,
# run the full banked program (tools/tpu_window.py) and exit 0 so the
# caller is notified.  Exits 3 when the deadline passes with no window.
# Usage: tools/tpu_watch.sh [deadline_seconds]  (default 10h)
DEADLINE=${1:-36000}
START=$(date +%s)
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch.log}
cd "$(dirname "$0")/.."
while true; do
  NOW=$(date +%s)
  if [ $((NOW - START)) -gt "$DEADLINE" ]; then
    echo "$(date -Is) deadline reached, no healthy window" >> "$LOG"
    exit 3
  fi
  if timeout 120 python -c "import jax; d=jax.devices()[0]; assert d.platform=='tpu'" 2>/dev/null; then
    echo "$(date -Is) tunnel healthy — running window program" >> "$LOG"
    python tools/tpu_window.py >> "$LOG" 2>&1
    RC=$?
    echo "$(date -Is) window program rc=$RC" >> "$LOG"
    exit $RC
  fi
  echo "$(date -Is) tunnel down" >> "$LOG"
  sleep 600
done
