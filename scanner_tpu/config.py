"""User configuration (~/.scanner_tpu.toml).

Capability parity: reference scannerpy/config.py (Config:27-110 —
storage type/db_path, master/worker network addresses).
"""

from __future__ import annotations

import os
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomllib is vendored tomli
    import tomli as tomllib
from typing import Any, Dict, Optional

from .common import ScannerException

DEFAULT_PATH = os.path.expanduser("~/.scanner_tpu.toml")


def default_config() -> Dict[str, Any]:
    return {
        "storage": {
            # "posix" | "gcs" | "memory"; a gs://bucket/prefix db_path
            # selects gcs automatically (reference config.py:56)
            "type": "posix",
            "db_path": os.path.expanduser("~/.scanner_tpu/db"),
        },
        "network": {
            # empty master = run jobs in-process; set a hostname (even
            # "localhost") to connect to a cluster master
            "master": "",
            "master_port": 5000,
            "worker_port": 5001,
            # 0 disables the /metrics|/healthz|/statusz endpoint (the
            # default); any other value binds it on that port
            # (docs/observability.md)
            "metrics_port": 0,
        },
        "perf": {
            # directory for JAX's persistent compilation cache: jitted
            # kernel executables (one per bucket shape, see PERF.md §5)
            # survive process restarts instead of recompiling.  "" (the
            # default) disables; SCANNER_TPU_COMPILATION_CACHE overrides
            # per process.
            "compilation_cache_dir": "",
            # paged per-device HBM frame cache (engine/framecache.py):
            # decoded frames are pooled in keyframe-aligned pages and
            # reused across tasks (stencil overlap, Gather samplings,
            # hot clips) instead of re-decoding + re-staging.  On by
            # default; SCANNER_TPU_FRAME_CACHE=0 overrides per process.
            "frame_cache_enabled": True,
            # per-device capacity target in MB (LRU-evicted past it; a
            # firing hbm_pressure alert shrinks it further);
            # SCANNER_TPU_FRAME_CACHE_MB overrides per process.
            "frame_cache_mb": 256,
            # frames per cache page; 0 (the default) auto-derives the
            # smallest keyframe-interval multiple >= 32 so pages map
            # onto GOP-decodable units.
            "frame_cache_page_frames": 0,
            # whole-pipeline XLA fusion (graph/fusion.py): chains of
            # consecutive fusable device ops compile into ONE jitted
            # program per bucket, so op-boundary intermediates never
            # materialize in HBM.  On by default; SCANNER_TPU_FUSION=0
            # overrides per process (the staged-path A/B lever).
            "fusion_enabled": True,
            # minimum chain length the fusion planner will fuse (a
            # singleton IS the staged path; raise to bound planner
            # aggressiveness).
            "fusion_min_chain": 2,
        },
        "memory": {
            # memory observability (util/memstats.py): per-device HBM
            # gauges + the allocation ledger every engine-owned device
            # buffer registers in.  On by default (nanoseconds per
            # buffer); SCANNER_TPU_MEMSTATS=0 overrides per process.
            "enabled": True,
            # ledger entries named in an OOM/status memory report
            # (largest first); SCANNER_TPU_MEMSTATS_TOPN overrides.
            "report_top_n": 10,
        },
        "trace": {
            # distributed-tracing span recording (util/tracing.py):
            # task/stage/op spans, flight recorder, cross-host trace
            # assembly.  On by default (low overhead, docs/
            # observability.md); the SCANNER_TPU_TRACING env var
            # overrides per process.
            "enabled": True,
            # cross-host clock-offset estimation (util/clocksync.py):
            # NTP-style exchange piggybacked on heartbeats, published
            # as clock_offset gauges and carried on span batches.  The
            # SCANNER_TPU_CLOCKSYNC env var overrides per process.
            "clocksync_enabled": True,
            # rebase remote span timestamps onto master time during
            # trace assembly (GetTrace); per-call raw_clocks /
            # scanner_trace --raw-clocks is the escape hatch.
            "rebase_clocks": True,
        },
        "alerts": {
            # the health/SLO engine (util/health.py): declarative alert
            # rules evaluated in-process over the metrics registry,
            # rolled up into /healthz | /readyz | /alertz and
            # Client.health().  On by default (a ~1 Hz sample of the
            # rule-referenced series); SCANNER_TPU_HEALTH=0 overrides
            # per process.
            "enabled": True,
            # user alert rules appended to the built-in default
            # ruleset; ";"-separated clauses, grammar in
            # docs/observability.md §Health & SLOs.  "" = defaults only.
            "rules": "",
        },
        "remediation": {
            # the alert->action remediation controller
            # (engine/controller.py): autoscaling, preemption drain,
            # admission pause, frame-cache shrink, ladder re-warm.  On
            # by default; SCANNER_TPU_REMEDIATION=0 overrides per
            # process (the signal-only kill switch).
            "enabled": True,
            # dry-run: playbooks decide (cooldown/hysteresis/rate
            # limit, audit, metrics) but never invoke their action —
            # the staging-environment mode.
            "dry_run": False,
            # autoscaler replica bounds ([min,max]) used when a master
            # runs with autoscale=True (docs/robustness.md
            # §Remediation playbooks).
            "autoscale_min": 1,
            "autoscale_max": 8,
        },
        "robustness": {
            # write-ahead bulk journal (engine/journal.py): between
            # checkpoints the master appends completion/strike/
            # blacklist/admission events as checksummed segment
            # objects, so a master kill -9 mid-bulk loses ZERO
            # acknowledged completions (docs/robustness.md §Durable
            # control plane).  On by default; SCANNER_TPU_JOURNAL=0
            # overrides per process (recovery then rides the
            # checkpoint window alone).
            "journal_enabled": True,
            # records per journal segment before rotation (bounds the
            # open-segment rewrite cost and the per-segment blast
            # radius of a torn tail); SCANNER_TPU_JOURNAL_ROTATE
            # overrides per process.
            "journal_rotate_records": 256,
        },
        "gang": {
            # gang-scheduled multi-host execution (engine/gang.py,
            # docs/robustness.md §Gang scheduling): a bulk with
            # PerfParams.gang_hosts > 0 co-schedules each task onto a
            # gang of live workers that rendezvous into one
            # jax.distributed runtime.  On by default (inert unless a
            # bulk asks); SCANNER_TPU_GANG=0 overrides per process.
            "enabled": True,
            # bound on the jax.distributed rendezvous at gang start —
            # a lost member must not pin the survivors in initialize
            # forever; SCANNER_TPU_GANG_INIT_TIMEOUT overrides.
            "init_timeout_s": 60,
            # how long the master waits for a full gang_hosts pool
            # before forming on whatever capacity HAS pooled (the
            # loss-tolerant re-form path);
            # SCANNER_TPU_GANG_FORM_TIMEOUT overrides.
            "form_timeout_s": 5,
            # mesh-partitioned gang evaluation: each member evaluates
            # only its row shard and member 0 assembles the output
            # over the interconnect (~N× per-gang throughput); off =
            # the replicated N×-redundant evaluation.  The master's
            # value decides per gang; SCANNER_TPU_GANG_SHARDED
            # overrides per process.
            "sharded": True,
            # stencil boundary rows exchange between neighbor members
            # over the mesh (parallel/halo.py) instead of each member
            # decoding past its shard edge; SCANNER_TPU_GANG_HALO
            # overrides per process.
            "halo_exchange": True,
        },
        "control": {
            # master shards in the horizontally sharded control plane
            # (engine/shardmap.py, docs/robustness.md §Sharded control
            # plane): bulks partition across this many masters by
            # consistent hash on the admission token.  1 (the default)
            # is the classic single-master cluster, bit-for-bit;
            # SCANNER_TPU_CONTROL_SHARDS overrides per process.
            "shards": 1,
        },
        "faults": {
            # deterministic fault-injection plan (docs/robustness.md for
            # the clause syntax; util/faults.py implements it).  "" (the
            # default) disarms every injection site; the
            # SCANNER_TPU_FAULTS env var overrides per process.  NEVER
            # set in production config — this exists for chaos testing.
            "plan": "",
        },
    }


def dump_toml(cfg: Dict[str, Any]) -> str:
    """Minimal TOML writer (the environment has no toml-writing lib)."""
    lines = []
    for section, values in cfg.items():
        lines.append(f"[{section}]")
        for k, v in values.items():
            if isinstance(v, str):
                lines.append(f'{k} = "{v}"')
            elif isinstance(v, bool):
                lines.append(f"{k} = {str(v).lower()}")
            else:
                lines.append(f"{k} = {v}")
        lines.append("")
    return "\n".join(lines)


class Config:
    def __init__(self, config_path: Optional[str] = None,
                 db_path: Optional[str] = None):
        path = config_path or DEFAULT_PATH
        cfg = default_config()
        if os.path.exists(path):
            with open(path, "rb") as f:
                loaded = tomllib.load(f)
            for section, values in loaded.items():
                cfg.setdefault(section, {}).update(values)
        elif config_path is not None:
            raise ScannerException(f"config file not found: {config_path}")
        if db_path is not None:
            cfg["storage"]["db_path"] = db_path
        self.config = cfg
        self.config_path = path

    @property
    def storage_type(self) -> str:
        return self.config["storage"]["type"]

    @property
    def db_path(self) -> str:
        return self.config["storage"]["db_path"]

    @property
    def master_address(self) -> Optional[str]:
        """host:port of the cluster master, or None for in-process
        execution.  Accepts either master/master_port or a combined
        master_address key."""
        n = self.config["network"]
        if n.get("master_address"):
            return n["master_address"]
        if n.get("master"):
            return f"{n['master']}:{n['master_port']}"
        return None

    @property
    def compilation_cache_dir(self) -> Optional[str]:
        """Persistent XLA compilation-cache directory, or None when
        disabled (the default)."""
        d = self.config.get("perf", {}).get("compilation_cache_dir", "")
        return d or None

    @property
    def frame_cache_enabled(self) -> bool:
        """Paged per-device HBM frame cache (the deployment default;
        SCANNER_TPU_FRAME_CACHE overrides per process)."""
        return bool(self.config.get("perf", {}).get(
            "frame_cache_enabled", True))

    @property
    def frame_cache_mb(self) -> int:
        """Per-device frame-cache capacity target in MB
        (SCANNER_TPU_FRAME_CACHE_MB overrides per process)."""
        return int(self.config.get("perf", {}).get("frame_cache_mb",
                                                   256))

    @property
    def frame_cache_page_frames(self) -> int:
        """Frames per frame-cache page (0 = keyframe-aligned auto)."""
        return int(self.config.get("perf", {}).get(
            "frame_cache_page_frames", 0))

    @property
    def fusion_enabled(self) -> bool:
        """Whole-pipeline XLA fusion of device op chains (the
        deployment default; SCANNER_TPU_FUSION overrides per
        process)."""
        return bool(self.config.get("perf", {}).get("fusion_enabled",
                                                    True))

    @property
    def fusion_min_chain(self) -> int:
        """Minimum member count the fusion planner fuses (>= 2)."""
        return int(self.config.get("perf", {}).get("fusion_min_chain",
                                                   2))

    @property
    def memstats_enabled(self) -> bool:
        """Memory accounting (HBM gauges + allocation ledger; the
        deployment default — SCANNER_TPU_MEMSTATS overrides)."""
        return bool(self.config.get("memory", {}).get("enabled", True))

    @property
    def memstats_report_top_n(self) -> int:
        """Ledger entries named in a memory report, largest first."""
        return int(self.config.get("memory", {}).get("report_top_n", 10))

    @property
    def tracing_enabled(self) -> bool:
        """Distributed-tracing span recording (the deployment default;
        SCANNER_TPU_TRACING overrides per process)."""
        return bool(self.config.get("trace", {}).get("enabled", True))

    @property
    def clocksync_enabled(self) -> bool:
        """Cross-host clock-offset estimation (the deployment default;
        SCANNER_TPU_CLOCKSYNC overrides per process)."""
        return bool(self.config.get("trace", {}).get(
            "clocksync_enabled", True))

    @property
    def rebase_clocks(self) -> bool:
        """Rebase remote span timestamps onto master time during trace
        assembly (per-call raw_clocks is the escape hatch)."""
        return bool(self.config.get("trace", {}).get(
            "rebase_clocks", True))

    @property
    def alerts_enabled(self) -> bool:
        """Health/SLO alert engine (the deployment default;
        SCANNER_TPU_HEALTH overrides per process)."""
        return bool(self.config.get("alerts", {}).get("enabled", True))

    @property
    def alert_rules(self) -> str:
        """User alert rules ([alerts] rules clause spec), "" = only the
        built-in default ruleset."""
        return str(self.config.get("alerts", {}).get("rules", "") or "")

    @property
    def remediation_enabled(self) -> bool:
        """Alert->action remediation controller (the deployment
        default; SCANNER_TPU_REMEDIATION overrides per process)."""
        return bool(self.config.get("remediation", {}).get("enabled",
                                                           True))

    @property
    def remediation_dry_run(self) -> bool:
        """Remediation dry-run: decisions audit but never actuate."""
        return bool(self.config.get("remediation", {}).get("dry_run",
                                                           False))

    @property
    def remediation_autoscale_bounds(self) -> tuple:
        """(min, max) worker replica bounds for the autoscaler."""
        r = self.config.get("remediation", {})
        return (int(r.get("autoscale_min", 1)),
                int(r.get("autoscale_max", 8)))

    @property
    def journal_enabled(self) -> bool:
        """Write-ahead bulk journal (the deployment default;
        SCANNER_TPU_JOURNAL overrides per process)."""
        return bool(self.config.get("robustness", {}).get(
            "journal_enabled", True))

    @property
    def journal_rotate_records(self) -> int:
        """Records per journal segment before rotation
        (SCANNER_TPU_JOURNAL_ROTATE overrides per process)."""
        return int(self.config.get("robustness", {}).get(
            "journal_rotate_records", 256))

    @property
    def gang_enabled(self) -> bool:
        """Gang-scheduled multi-host execution (the deployment
        default; SCANNER_TPU_GANG overrides per process)."""
        return bool(self.config.get("gang", {}).get("enabled", True))

    @property
    def gang_init_timeout_s(self) -> float:
        """Rendezvous bound for gang members
        (SCANNER_TPU_GANG_INIT_TIMEOUT overrides per process)."""
        return float(self.config.get("gang", {}).get("init_timeout_s",
                                                     60))

    @property
    def gang_form_timeout_s(self) -> float:
        """How long the master holds out for a full gang before
        forming on the pooled capacity
        (SCANNER_TPU_GANG_FORM_TIMEOUT overrides per process)."""
        return float(self.config.get("gang", {}).get("form_timeout_s",
                                                     5))

    @property
    def gang_sharded(self) -> bool:
        """Mesh-partitioned gang evaluation — members evaluate only
        their row shard (the deployment default;
        SCANNER_TPU_GANG_SHARDED overrides per process)."""
        return bool(self.config.get("gang", {}).get("sharded", True))

    @property
    def gang_halo_exchange(self) -> bool:
        """Stencil boundary rows exchange between neighbor members
        over the mesh instead of decoding redundantly (the deployment
        default; SCANNER_TPU_GANG_HALO overrides per process)."""
        return bool(self.config.get("gang", {}).get("halo_exchange",
                                                    True))

    @property
    def control_shards(self) -> int:
        """Master shard count for the sharded control plane (the
        deployment default; SCANNER_TPU_CONTROL_SHARDS overrides per
        process)."""
        return int(self.config.get("control", {}).get("shards", 1))

    @property
    def faults_plan(self) -> Optional[str]:
        """Armed fault-injection plan spec, or None (the default: all
        injection sites disabled, zero overhead)."""
        plan = self.config.get("faults", {}).get("plan", "")
        return plan or None

    @property
    def metrics_port(self) -> Optional[int]:
        """Port for the live /metrics endpoint, or None when disabled
        (the default: telemetry serving is strictly opt-in)."""
        port = int(self.config["network"].get("metrics_port", 0) or 0)
        return port or None

    @staticmethod
    def write_default(path: str = DEFAULT_PATH) -> str:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(dump_toml(default_config()))
        return path
