"""Common types shared across the framework.

Capability parity: reference scannerpy/common.py (DeviceType:36, CacheMode:72,
PerfParams:78) — re-designed for a host+TPU execution model rather than
CPU/GPU kernel placement.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


class ScannerException(Exception):
    """Base exception for all framework errors."""


class GraphException(ScannerException):
    """Raised when a computation graph is malformed."""


class JobException(ScannerException):
    """Raised when a bulk job fails."""


class StorageException(ScannerException):
    """Raised on storage backend errors."""


class DeviceOutOfMemory(ScannerException):
    """Device memory exhaustion (RESOURCE_EXHAUSTED) observed at an
    engine staging/dispatch site — classified transient so the master
    requeues the task strike-free after its staged buffers are freed
    (util/memstats.py OOM forensics; the `memory.pressure` fault site
    raises this to force the path deterministically on CPU)."""


class DeviceType(enum.Enum):
    """Where a kernel runs.

    The reference dispatches CPU vs GPU (common.h:53-82); here the split is
    host (numpy, C++ helpers) vs TPU (JAX/XLA programs).  DeviceType.GPU is
    accepted as an alias for TPU so reference-style scripts keep working.
    """

    CPU = "cpu"
    TPU = "tpu"
    GPU = "tpu"  # alias: accelerator

    @property
    def is_accelerator(self) -> bool:
        return self is not DeviceType.CPU


class FrameType:
    """Marker type for video-frame columns in kernel type annotations."""


class BlobType:
    """Marker type for raw-bytes columns."""


class CacheMode(enum.Enum):
    """What to do when a job's output stream already exists.

    Mirrors reference CacheMode (common.py:72): Error refuses, Ignore skips
    already-committed outputs (job-level resume), Overwrite recomputes.
    """

    Error = 0
    Ignore = 1
    Overwrite = 2


class BoundaryCondition(enum.Enum):
    """Stencil boundary handling. Only REPEAT_EDGE is supported, matching the
    reference (assert at evaluate_worker.cpp:413)."""

    REPEAT_EDGE = 0


@dataclass
class PerfParams:
    """Performance knobs for a bulk job.

    io_packet_size: rows per storage/decode unit of work (task granularity is
      a multiple of this); work_packet_size: rows per compute batch pushed to
      a kernel group (the XLA batch dimension).
    Mirrors reference PerfParams (common.py:78-160) with TPU-centric defaults.
    """

    work_packet_size: int = 16
    io_packet_size: int = 64
    # Evaluator pipeline instances per node.  None resolves at job launch
    # (engine/evaluate.py default_pipeline_instances): one device-affine
    # instance per local chip on multi-device accelerator hosts —
    # instance i owns chip i, stages its tasks' inputs there and runs
    # the shared jitted kernels on it — and 1 elsewhere.  An explicit
    # value here (or on the Client/Worker constructor) always wins;
    # SCANNER_TPU_DEVICE_AFFINITY=0 disables the per-chip resolution
    # and pinning entirely (the A/B lever).
    pipeline_instances_per_node: Optional[int] = None
    load_sparsity_threshold: int = 8
    queue_size_per_pipeline: int = 4
    task_timeout: float = 0.0  # seconds; 0 = no timeout
    checkpoint_frequency: int = 10
    # profiling detail recorded during the job: 0 = coarse stage spans
    # only, 1 = per-task detail (default), 2 = verbose (reference
    # rpc.proto:270-275 profiler_level)
    profiler_level: int = 1
    # Opt-in task affinity for unbounded-state ops: consecutive tasks of
    # a job carry kernel state forward instead of recomputing rows
    # 0..end per task — O(n) total work instead of O(n^2/io_packet) on
    # long un-sliced streams (the reference pins a job's packets to one
    # worker, worker.cpp:373-415 save_coordinator).  Evaluation of such
    # a job serializes onto one pipeline instance (and, in a cluster,
    # one worker per job); any break in the chain — reordering, a
    # failed task, worker death — falls back to the self-contained
    # recompute, so results never depend on the affinity holding.
    stateful_task_affinity: bool = False
    # Work-packet streaming: a task's io packet never materializes
    # whole — the loader decodes work-packet-sized chunks through an
    # incremental decoder session and the evaluator consumes them as
    # they arrive, carrying kernel state across chunk boundaries.
    # Bounds peak memory to a few work packets per task (the 4K case)
    # and overlaps decode/h2d/compute inside a task (reference element
    # cache + feeder threads, evaluate_worker.h:207-218).
    # SCANNER_TPU_STREAM_PACKETS=0 is the global kill switch.
    stream_work_packets: bool = True
    # Gang-scheduled multi-host execution (engine/gang.py,
    # docs/robustness.md §Gang scheduling): >0 asks the master to
    # co-schedule each task onto a GANG of up to this many live
    # workers instead of handing it to one puller — the members
    # rendezvous into one jax.distributed runtime (member 0 is the
    # coordinator), each evaluates the task REPLICATED (deterministic
    # redundancy, not a sharded speedup — this knob buys failure
    # semantics, N× the compute), stages its per-host shard of the
    # result digest via host_local_array and agrees through one
    # cross-host collective reduction, and commits through member 0
    # alone (exactly-once sink).  Every gang RPC is fenced by
    # (gang_id, gang_epoch): any member loss aborts the gang, bumps
    # the epoch and re-forms on the remaining capacity, strike-free.
    # 0 (default) = ordinary independent task pulls; local
    # (in-process) runs treat any value as a single-host gang and
    # execute normally.
    gang_hosts: int = 0
    # Mesh-partitioned gang evaluation (default): each member loads,
    # decodes and evaluates ONLY its contiguous row shard of every
    # task (shard_range over the gang mesh), stencil boundary rows
    # move between neighbors over the interconnect (parallel/halo.py)
    # instead of widening each member's decode, and member 0 — still
    # the single writer — assembles the per-member output shards over
    # one all-gather and commits after the digest collective agrees:
    # per-gang throughput is ~N× the replicated path's.  False = the
    # pre-sharding replicated evaluation (every member computes all
    # rows; N× redundancy, kept as the A/B + fallback mode).  A
    # re-formed smaller gang just recomputes shard_range at the new
    # member count.  Effective only with gang_hosts > 0; the master's
    # [gang] sharded config must also be on.
    gang_sharded: bool = True

    # reference-compat kwargs that are meaningless on TPU and accepted but
    # ignored (XLA owns device/host memory pooling; there is no CUDA pool
    # to size — reference common.py cpu_pool/gpu_pool)
    _IGNORED_KWARGS = ("cpu_pool", "gpu_pool", "pinned_cpu_pool")

    @classmethod
    def _strip_ignored(cls, kw: dict) -> dict:
        for k in cls._IGNORED_KWARGS:
            kw.pop(k, None)
        return kw

    @classmethod
    def manual(cls, work_packet_size: int, io_packet_size: int, **kw) -> "PerfParams":
        if io_packet_size % work_packet_size != 0:
            raise ScannerException(
                f"io_packet_size ({io_packet_size}) must be a multiple of "
                f"work_packet_size ({work_packet_size})")
        return cls(work_packet_size=work_packet_size,
                   io_packet_size=io_packet_size, **cls._strip_ignored(kw))

    @classmethod
    def estimate(cls, **kw) -> "PerfParams":
        """Auto-tuned variant; heuristics are applied at job-launch time when
        stream geometry is known (engine/executor.py)."""
        p = cls(**cls._strip_ignored(kw))
        p._estimate = True  # type: ignore[attr-defined]
        return p


class NullElement:
    """Placeholder for a null row produced by RepeatNull spacing or missed
    dependencies (reference storage.py:8)."""

    _instance: Optional["NullElement"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "NullElement"

    def __reduce__(self):
        return (NullElement, ())


class SliceList(list):
    """Marks a per-job argument list as being per-slice-group rather than a
    plain value (reference op.py SliceList)."""
