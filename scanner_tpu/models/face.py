"""Face detection and embedding.

Capability parity: reference examples/apps/face_detection (MTCNN-style
kernel) and the multi-worker face-embedding baseline config
(BASELINE.json config 5).  Detection reuses the SSD family with a
face-tuned anchor set; embeddings come from a compact backbone + projection
head with L2-normalized output.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op
from .detection import ObjectDetect
from .nets import Backbone


@register_op(name="FaceDetect", device=DeviceType.TPU, batch=8)
class FaceDetect(ObjectDetect):
    """SSD detector with face-tuned defaults (reference face_detection
    app).  Width-8 instances restore the shipped face-task weights
    (models/weights/face_ssd_w8.npz, models/detect_train.py) unless a
    checkpoint is given or pretrained=False."""

    _shipped = "face_ssd_w8.npz"
    _shipped_width = 8

    def __init__(self, config, width: int = 32, score_thresh: float = 0.1,
                 seed: int = 1, checkpoint_dir: Optional[str] = None,
                 pretrained: bool = True):
        super().__init__(config, width=width, num_classes=2,
                         score_thresh=score_thresh, seed=seed,
                         checkpoint_dir=checkpoint_dir,
                         pretrained=pretrained)


class EmbeddingNet(nn.Module):
    dim: int = 128
    width: int = 32
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images):
        feat = Backbone(width=self.width, dtype=self.dtype)(images)
        pooled = feat.mean(axis=(1, 2))
        emb = nn.Dense(self.dim, dtype=jnp.float32)(pooled)
        # zero inputs (e.g. a crop that fell outside the frame) must yield
        # a zero vector, not 0/0 = NaN
        norm = jnp.linalg.norm(emb, axis=-1, keepdims=True)
        return emb / jnp.maximum(norm, 1e-12)


@register_op(device=DeviceType.TPU, batch=16)
class FaceEmbedding(Kernel):
    """L2-normalized face/crop embedding vectors (reference face-embedding
    pipeline, BASELINE config 5).  Width-8/dim-128 instances restore the
    shipped identity-metric weights (models/weights/embed_w8.npz,
    models/detect_train.py) unless a checkpoint is given or
    pretrained=False."""

    _shipped = "embed_w8.npz"
    _shipped_width = 8

    def __init__(self, config, dim: int = 128, width: int = 32,
                 seed: int = 2, checkpoint_dir: Optional[str] = None,
                 pretrained: bool = True):
        super().__init__(config)
        self.model = EmbeddingNet(dim=dim, width=width)
        from .checkpoint import init_or_restore, shipped_weights
        from .infer import DataParallelApply
        if checkpoint_dir is None and pretrained \
                and width == self._shipped_width and dim == 128:
            checkpoint_dir = shipped_weights(self._shipped)
        params = init_or_restore(
            self.model, jax.random.PRNGKey(seed),
            jnp.zeros((1, 128, 128, 3), jnp.uint8), checkpoint_dir)
        # dp-shard batches over every chip the engine handed this kernel
        self._dp = DataParallelApply(jax.jit(self.model.apply), params,
                                     config.devices)
        self.params = self._dp.params

    def infer_cost_flops(self, batch):
        """XLA-reported FLOPs for one inference call on `batch` (for
        the bench's MFU accounting); None when unavailable."""
        return self._dp.cost_flops(jnp.asarray(batch))

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        # (B, dim) embeddings returned without a host sync (device arrays
        # chain through the column store; the sink fetches once per task)
        return self._dp(jnp.asarray(frame))
