"""Train the instance segmenter on a synthetic shape task and ship
weights.

The reference detectron app loads externally-trained Mask R-CNN weights
(examples/apps/detectron/main.py); this framework trains its own with
reproducible provenance, like the other model families
(models/detect_train.py).  Task: 1..3 bright shapes — axis-aligned
rectangles or inscribed ellipses — on a noisy dark background; the
detector must find the boxes and the mask head must recover each shape's
silhouette (a rectangle fills its box, an ellipse does not — the mask
head has to actually read the pixels).

Ground-truth masks are analytic: for a box and a shape kind the roi-grid
mask is computed in closed form (`roi_gt_mask`), and full-frame masks for
evaluation come from `full_gt_mask` — no rasterize/crop/resample chain to
introduce label noise.

`python -m scanner_tpu.models.seg_train <out_dir>` trains and exports a
portable .npz (models/weights/seg_w8.npz ships it).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .detect_train import SIZE, WIDTH, match_anchors

KIND_RECT = 0
KIND_ELLIPSE = 1
TRAIN_ROIS = 4          # fixed gt-roi budget per training frame


def render_shape_scene(rng: np.random.RandomState, size: int = SIZE,
                       max_objects: int = 3
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Noisy dark frame with 1..max_objects bright shapes.  Returns
    (frame uint8 (S,S,3), boxes (N,4) unit [y1,x1,y2,x2], kinds (N,)
    int32 — KIND_RECT or KIND_ELLIPSE)."""
    frame = rng.randint(0, 40, (size, size, 3)).astype(np.uint8)
    ys, xs = np.mgrid[0:size, 0:size]
    n = rng.randint(1, max_objects + 1)
    boxes, kinds = [], []
    for _ in range(n):
        h = rng.randint(12, 28)
        w = rng.randint(12, 28)
        y = rng.randint(0, size - h)
        x = rng.randint(0, size - w)
        color = rng.randint(170, 255, 3)
        kind = int(rng.randint(0, 2))
        if kind == KIND_RECT:
            frame[y:y + h, x:x + w] = color
        else:
            cy, cx = y + h / 2, x + w / 2
            inside = (((ys - cy) / (h / 2)) ** 2 +
                      ((xs - cx) / (w / 2)) ** 2) <= 1.0
            frame[inside] = color
        boxes.append([y / size, x / size, (y + h) / size, (x + w) / size])
        kinds.append(kind)
    return frame, np.asarray(boxes, np.float32), np.asarray(kinds, np.int32)


def roi_gt_mask(box: np.ndarray, kind: int, roi: np.ndarray,
                mask_size: int) -> np.ndarray:
    """Analytic (M, M) binary mask of a shape (gt `box` + `kind`) sampled
    on the grid of an arbitrary `roi` (both unit corners).  Sampling on
    the roi grid rather than the box grid lets training jitter its rois —
    the mask head then learns the shape's actual boundary instead of
    "fill the roi"."""
    M = mask_size
    c = (np.arange(M, dtype=np.float32) + 0.5) / M
    yu = roi[0] + (roi[2] - roi[0]) * c
    xu = roi[1] + (roi[3] - roi[1]) * c
    y1, x1, y2, x2 = box
    if kind == KIND_RECT:
        iny = (yu >= y1) & (yu < y2)
        inx = (xu >= x1) & (xu < x2)
        return (iny[:, None] & inx[None, :]).astype(np.float32)
    cy, cx = (y1 + y2) / 2, (x1 + x2) / 2
    ry, rx = max((y2 - y1) / 2, 1e-6), max((x2 - x1) / 2, 1e-6)
    dy = ((yu - cy) / ry) ** 2
    dx = ((xu - cx) / rx) ** 2
    return ((dy[:, None] + dx[None, :]) <= 1.0).astype(np.float32)


def jitter_box(rng: np.random.RandomState, box: np.ndarray,
               frac: float = 0.12) -> np.ndarray:
    """Shift/scale a unit-coordinate box by up to ±frac of its size —
    the training-time stand-in for imperfect detector boxes."""
    y1, x1, y2, x2 = box
    h, w = y2 - y1, x2 - x1
    dy1, dy2 = rng.uniform(-frac, frac, 2) * h
    dx1, dx2 = rng.uniform(-frac, frac, 2) * w
    out = np.asarray([y1 + dy1, x1 + dx1, y2 + dy2, x2 + dx2], np.float32)
    out[2] = max(out[2], out[0] + 1e-3)
    out[3] = max(out[3], out[1] + 1e-3)
    return np.clip(out, 0.0, 1.0)


def full_gt_mask(box: np.ndarray, kind: int, height: int,
                 width: int) -> np.ndarray:
    """Full-frame (H, W) boolean mask of one ground-truth shape."""
    y1, x1, y2, x2 = box
    ys, xs = np.mgrid[0:height, 0:width]
    yu = (ys + 0.5) / height
    xu = (xs + 0.5) / width
    in_box = (yu >= y1) & (yu < y2) & (xu >= x1) & (xu < x2)
    if kind == KIND_RECT:
        return in_box
    cy, cx = (y1 + y2) / 2, (x1 + x2) / 2
    ry, rx = (y2 - y1) / 2, (x2 - x1) / 2
    return (((yu - cy) / max(ry, 1e-6)) ** 2 +
            ((xu - cx) / max(rx, 1e-6)) ** 2) <= 1.0


def synth_shape_video(path: str, num_frames: int = 16, size: int = SIZE,
                      fps: float = 24.0, seed: int = 17):
    """Encode a clip of independent shape scenes; returns the per-frame
    (boxes, kinds) ground truth (crf 14 keeps silhouettes crisp)."""
    from ..video.ingest import encode_frames_mp4

    rng = np.random.RandomState(seed)
    frames, gts = [], []
    for _ in range(num_frames):
        f, boxes, kinds = render_shape_scene(rng, size)
        frames.append(f)
        gts.append((boxes, kinds))
    encode_frames_mp4(path, frames, size, size, fps=fps, keyint=8, crf=14)
    return gts


def seg_batch(rng: np.random.RandomState, batch: int, anchors: np.ndarray,
              mask_size: int, size: int = SIZE):
    """One training batch: (frames (B,S,S,3) u8, cls (B,N) i32,
    deltas (B,N,4) f32, rois (B,K,4) f32, roi_masks (B,K,M,M) f32,
    roi_valid (B,K) f32) — rois are JITTERED ground-truth boxes (the
    Mask R-CNN training-time roi source, with detector-noise
    augmentation), zero-padded to K=TRAIN_ROIS; mask targets are the
    shapes resampled on each jittered roi's grid."""
    N = anchors.shape[0]
    K, M = TRAIN_ROIS, mask_size
    frames = np.zeros((batch, size, size, 3), np.uint8)
    cls = np.zeros((batch, N), np.int32)
    deltas = np.zeros((batch, N, 4), np.float32)
    rois = np.zeros((batch, K, 4), np.float32)
    roi_masks = np.zeros((batch, K, M, M), np.float32)
    roi_valid = np.zeros((batch, K), np.float32)
    for b in range(batch):
        frames[b], boxes, kinds = render_shape_scene(rng, size)
        cls[b], deltas[b] = match_anchors(anchors, boxes)
        for k in range(min(len(boxes), K)):
            roi = jitter_box(rng, boxes[k])
            rois[b, k] = roi
            roi_masks[b, k] = roi_gt_mask(boxes[k], int(kinds[k]), roi, M)
            roi_valid[b, k] = 1.0
    return frames, cls, deltas, rois, roi_masks, roi_valid


def train_segmenter(checkpoint_dir: str, steps: int = 400, batch: int = 4,
                    size: int = SIZE, width: int = WIDTH, seed: int = 3,
                    export_npz: Optional[str] = None,
                    log_every: int = 50) -> float:
    """Train InstanceSegmentor: SSD detection loss + per-roi mask BCE on
    ground-truth rois.  Orbax checkpoint + optional portable .npz export;
    returns the final loss."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..util.log import get_logger
    from .checkpoint import TrainCheckpointer, export_params_npz
    from .detection import make_anchors
    from .segmentation import MASK_SIZE, InstanceSegmentor

    log = get_logger("train")
    fh = fw = -(-size // 16)
    anchors_np = make_anchors(fh, fw)

    model = InstanceSegmentor(num_classes=2, width=width)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, size, size, 3), jnp.uint8))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, frames, cls_t, box_t, rois, masks_t, roi_valid):
        logits, deltas, mask_logits = model.apply(p, frames, rois)
        valid = (cls_t >= 0)
        pos = (cls_t == 1)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.maximum(cls_t, 0))
        w = jnp.where(pos, 10.0, 1.0) * valid
        cls_loss = (ce * w).sum() / jnp.maximum(w.sum(), 1.0)
        hub = optax.huber_loss(deltas, box_t).sum(-1)
        box_loss = (hub * pos).sum() / jnp.maximum(pos.sum(), 1.0)
        bce = optax.sigmoid_binary_cross_entropy(
            mask_logits, masks_t).mean(axis=(-2, -1))
        mask_loss = (bce * roi_valid).sum() / \
            jnp.maximum(roi_valid.sum(), 1.0)
        # masks are the op's raison d'etre — keep their gradient from
        # being drowned by the dense anchor losses
        return cls_loss + box_loss + 2.0 * mask_loss

    @jax.jit
    def step_fn(p, s, *batch_args):
        loss, grads = jax.value_and_grad(loss_fn)(p, *batch_args)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    rng = np.random.RandomState(seed)
    loss = float("nan")
    for i in range(steps):
        args = seg_batch(rng, batch, anchors_np, MASK_SIZE, size)
        params, opt_state, loss = step_fn(params, opt_state, *args)
        if log_every and (i + 1) % log_every == 0:
            log.info("seg_train step %d/%d loss=%.5f", i + 1, steps,
                     float(loss))
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        ckpt.save(steps, params, opt_state)
    finally:
        ckpt.close()
    if export_npz:
        export_params_npz(params, export_npz)
    return float(loss)


def main(argv: Optional[list] = None) -> None:
    import argparse
    import os

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out_dir")
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend before first backend use")
    args = ap.parse_args(argv)
    if args.cpu:
        from ..util.jaxenv import force_cpu_platform
        force_cpu_platform()
    os.makedirs(args.out_dir, exist_ok=True)
    loss = train_segmenter(
        os.path.join(args.out_dir, "seg_ckpt"), steps=args.steps,
        export_npz=os.path.join(args.out_dir, f"seg_w{WIDTH}.npz"))
    print(f"seg: final loss {loss:.5f}")


if __name__ == "__main__":
    main()
