"""Instance segmentation: detection + per-instance masks.

Capability parity: reference examples/apps/detectron (Mask R-CNN via the
Caffe2 detectron kernels, detectron_kernels.py) — rebuilt TPU-first:

* **Fixed shapes end to end.**  Mask R-CNN's dynamic proposal lists don't
  map to XLA; here detection keeps the packed (top_k, 6) contract of
  ObjectDetect and masks are a fixed (top_k, M, M) tensor — padding
  instances carry valid=0 instead of changing shapes.
* **ROI align as a vectorized bilinear gather** (`roi_align`): a K-roi
  S×S sampling grid evaluated with 4 clamped gathers + lerp, vmapped
  over rois and batch — no dynamic slicing, no host sync.
* **Two-level features.**  The SSD detection head reads the shared
  stride-16 backbone; masks read a dedicated stride-2 trunk (FPN-lite)
  so an object 16 px wide still spans 8 mask-feature cells.

The whole forward (backbone → head → decode → NMS → ROI align → mask
head) is ONE jitted function; results stay device-resident and are
fetched once per task at the sink, like the other model ops.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op
from .detection import SSDHead, make_anchors, pack_detections
from .nets import Backbone

ROI_SIZE = 8          # roi-align grid (mask head upsamples 2x)
MASK_SIZE = 2 * ROI_SIZE
TOP_K = 8             # fixed instance budget per frame


def roi_align(feat: jnp.ndarray, boxes: jnp.ndarray,
              out_size: int) -> jnp.ndarray:
    """Bilinear ROI align with fixed shapes.

    feat (B, fh, fw, C) float; boxes (B, K, 4) unit-coordinate corners
    [y1, x1, y2, x2] -> (B, K, S, S, C).  Each output cell samples the
    feature map at its roi-grid center with bilinear interpolation
    (4 clamped gathers); degenerate boxes just sample a point.
    """
    fh, fw = feat.shape[1], feat.shape[2]
    S = out_size
    cell = (jnp.arange(S, dtype=jnp.float32) + 0.5) / S

    def one_roi(fmap, box):
        ys = box[0] + (box[2] - box[0]) * cell          # unit coords
        xs = box[1] + (box[3] - box[1]) * cell
        fy = ys * fh - 0.5                              # pixel-center grid
        fx = xs * fw - 0.5
        yf = jnp.floor(fy)
        xf = jnp.floor(fx)
        wy = fy - yf
        wx = fx - xf
        # clamp each corner from the UNCLIPPED floor so out-of-range
        # samples degenerate to the edge value (both corners hit the same
        # edge row/col) instead of extrapolating inward
        y0 = jnp.clip(yf.astype(jnp.int32), 0, fh - 1)
        y1 = jnp.clip(yf.astype(jnp.int32) + 1, 0, fh - 1)
        x0 = jnp.clip(xf.astype(jnp.int32), 0, fw - 1)
        x1 = jnp.clip(xf.astype(jnp.int32) + 1, 0, fw - 1)
        f00 = fmap[y0[:, None], x0[None, :]]            # (S, S, C)
        f01 = fmap[y0[:, None], x1[None, :]]
        f10 = fmap[y1[:, None], x0[None, :]]
        f11 = fmap[y1[:, None], x1[None, :]]
        wy = wy[:, None, None]
        wx = wx[None, :, None]
        return (f00 * (1 - wy) * (1 - wx) + f01 * (1 - wy) * wx +
                f10 * wy * (1 - wx) + f11 * wy * wx)

    per_image = jax.vmap(one_roi, in_axes=(None, 0))     # over K rois
    return jax.vmap(per_image)(feat, boxes)              # over batch


class MaskTrunk(nn.Module):
    """Stride-2 mask feature extractor (FPN-lite level for ROI align) —
    high-resolution on purpose: a 16 px object still spans 8 mask-feature
    cells, so silhouette boundaries survive to the roi grid."""

    width: int = 32
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images):
        x = images.astype(self.dtype) / 255.0
        x = nn.Conv(self.width, (5, 5), strides=(2, 2), dtype=self.dtype,
                    padding="SAME")(x)
        x = nn.GroupNorm(num_groups=min(8, self.width), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.width, (3, 3), dtype=self.dtype,
                    padding="SAME")(x)
        x = nn.GroupNorm(num_groups=min(8, self.width), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.width, (3, 3), dtype=self.dtype,
                    padding="SAME")(x)
        return nn.relu(x)


class MaskHead(nn.Module):
    """(…, S, S, C) roi features -> (…, 2S, 2S) mask logits."""

    width: int = 32
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, roi_feat):
        h = roi_feat.astype(self.dtype)
        h = nn.Conv(2 * self.width, (3, 3), dtype=self.dtype,
                    padding="SAME")(h)
        h = nn.relu(h)
        h = nn.Conv(2 * self.width, (3, 3), dtype=self.dtype,
                    padding="SAME")(h)
        h = nn.relu(h)
        h = nn.ConvTranspose(self.width, (2, 2), strides=(2, 2),
                             dtype=self.dtype)(h)
        h = nn.relu(h)
        return nn.Conv(1, (1, 1), dtype=jnp.float32)(h)[..., 0]


class InstanceSegmentor(nn.Module):
    """SSD detection + per-roi mask prediction over shared inputs."""

    num_classes: int = 2
    width: int = 32
    roi_size: int = ROI_SIZE
    dtype: Any = jnp.bfloat16

    def setup(self):
        self.backbone = Backbone(width=self.width, dtype=self.dtype)
        self.det_head = SSDHead(num_classes=self.num_classes,
                                dtype=self.dtype)
        self.mask_trunk = MaskTrunk(width=self.width, dtype=self.dtype)
        self.mask_head = MaskHead(width=self.width, dtype=self.dtype)

    def detect(self, images):
        return self.det_head(self.backbone(images))

    def roi_masks(self, images, rois):
        """rois (B, K, 4) unit corners -> (B, K, 2*roi_size, 2*roi_size)
        mask logits."""
        mf = self.mask_trunk(images).astype(jnp.float32)
        return self.mask_head(roi_align(mf, rois, self.roi_size))

    def __call__(self, images, rois=None):
        if rois is None:  # init-time shape probe: any fixed-K roi set
            rois = jnp.zeros((images.shape[0], TOP_K, 4), jnp.float32)
        cls, deltas = self.detect(images)
        return cls, deltas, self.roi_masks(images, rois)


def unpack_instances(row, mask_thresh: float = 0.5,
                     mask_size: int = MASK_SIZE) -> Dict[str, np.ndarray]:
    """Unpack one stored InstanceSegment row — a (top_k, 6 + M*M) array
    [y1, x1, y2, x2, score, valid, mask probs…] — into
    {"boxes": (n, 4), "scores": (n,), "masks": (n, M, M) bool},
    dropping padding instances."""
    a = np.asarray(row, np.float32)
    keep = a[:, 5] > 0.5
    a = a[keep]
    masks = a[:, 6:].reshape(-1, mask_size, mask_size) > mask_thresh
    return {"boxes": a[:, :4], "scores": a[:, 4], "masks": masks}


def paste_masks(boxes: np.ndarray, masks: np.ndarray, height: int,
                width: int) -> np.ndarray:
    """Paste per-roi boolean masks (n, M, M) into full-frame boolean masks
    (n, H, W) by nearest-neighbor resampling inside each box (the
    detectron visualization step, host-side numpy)."""
    n = len(boxes)
    M = masks.shape[1] if n else 0
    out = np.zeros((n, height, width), bool)
    for i in range(n):
        y1, x1, y2, x2 = boxes[i]
        py1 = int(np.clip(round(y1 * height), 0, height - 1))
        px1 = int(np.clip(round(x1 * width), 0, width - 1))
        py2 = int(np.clip(round(y2 * height), py1 + 1, height))
        px2 = int(np.clip(round(x2 * width), px1 + 1, width))
        h, w = py2 - py1, px2 - px1
        yy = np.clip(((np.arange(h) + 0.5) * M / h - 0.5).round(),
                     0, M - 1).astype(int)
        xx = np.clip(((np.arange(w) + 0.5) * M / w - 0.5).round(),
                     0, M - 1).astype(int)
        out[i, py1:py2, px1:px2] = masks[i][yy[:, None], xx[None, :]]
    return out


@register_op(device=DeviceType.TPU, batch=4)
class InstanceSegment(Kernel):
    """Per-frame instance segmentation as packed (top_k, 6 + M*M) rows —
    [y1, x1, y2, x2, score, valid] + an M×M mask probability grid per
    instance, unit coordinates — decode with `unpack_instances` /
    `paste_masks` (reference detectron app equivalent).

    With no `checkpoint_dir`, width-8 instances restore the shipped
    synthetic-shape-task weights (models/weights/seg_w8.npz, provenance
    models/seg_train.py); pass `pretrained=False` for random init."""

    _shipped = "seg_w8.npz"
    _shipped_width = 8

    def __init__(self, config, width: int = 32, num_classes: int = 2,
                 score_thresh: float = 0.05, seed: int = 3,
                 checkpoint_dir: Optional[str] = None,
                 pretrained: bool = True):
        super().__init__(config)
        self.model = InstanceSegmentor(num_classes=num_classes, width=width)
        from .checkpoint import init_or_restore, shipped_weights
        if checkpoint_dir is None and pretrained \
                and width == self._shipped_width and num_classes == 2:
            checkpoint_dir = shipped_weights(self._shipped)
        self.params = init_or_restore(
            self.model, jax.random.PRNGKey(seed),
            jnp.zeros((1, 128, 128, 3), jnp.uint8), checkpoint_dir)
        self.score_thresh = float(score_thresh)
        self._anchors = {}

        thresh = self.score_thresh
        model = self.model

        @jax.jit
        def infer(params, images, anchors):
            def fwd(mdl, images):
                cls, deltas = mdl.detect(images)
                packed, sel = pack_detections(cls, deltas, anchors, thresh,
                                              top_k=TOP_K)
                mask_p = jax.nn.sigmoid(mdl.roi_masks(images, sel))
                B = sel.shape[0]
                return jnp.concatenate(
                    [packed,
                     mask_p.reshape(B, TOP_K, MASK_SIZE * MASK_SIZE)],
                    axis=-1)

            return model.apply(params, images, method=fwd)

        self._infer = infer

    def infer_cost_flops(self, batch):
        """XLA-reported FLOPs for one inference call on `batch` (for
        the bench's MFU accounting); None when unavailable."""
        from .detection import anchored_cost_flops
        return anchored_cost_flops(self, batch)

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        """Returns a (B, top_k, 6 + M*M) float32 batch, device-resident
        (single fetch per task at the sink, PERF.md §1)."""
        images = jnp.asarray(frame)
        fh = -(-images.shape[1] // 16)
        fw = -(-images.shape[2] // 16)
        if (fh, fw) not in self._anchors:
            self._anchors[(fh, fw)] = jnp.asarray(make_anchors(fh, fw))
        return self._infer(self.params, images, self._anchors[(fh, fw)])
