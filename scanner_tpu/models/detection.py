"""Single-shot object detection.

Capability parity: reference examples/apps/object_detection_tensorflow
(SSD mobilenet TF kernel) — rebuilt as an anchor-based SSD head over the
shared JAX backbone, with jit-compiled box decode and a vectorized NMS that
runs as a fixed-iteration lax loop (no data-dependent shapes on device).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op
from .nets import Backbone


def make_anchors(fh: int, fw: int, scales=(0.1, 0.25, 0.45),
                 ratios=(0.5, 1.0, 2.0)) -> np.ndarray:
    """(fh*fw*A, 4) anchors as [cy, cx, h, w] in unit coords."""
    ys = (np.arange(fh) + 0.5) / fh
    xs = (np.arange(fw) + 0.5) / fw
    anchors = []
    for y in ys:
        for x in xs:
            for s in scales:
                for r in ratios:
                    anchors.append([y, x, s * np.sqrt(r), s / np.sqrt(r)])
    return np.asarray(anchors, np.float32)


class SSDHead(nn.Module):
    num_classes: int = 2  # background + object
    anchors_per_cell: int = 9
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feat):
        A = self.anchors_per_cell
        cls = nn.Conv(A * self.num_classes, (3, 3), dtype=jnp.float32,
                      padding="SAME", name="cls")(feat)
        box = nn.Conv(A * 4, (3, 3), dtype=jnp.float32, padding="SAME",
                      name="box")(feat)
        B, fh, fw, _ = cls.shape
        return (cls.reshape(B, fh * fw * A, self.num_classes),
                box.reshape(B, fh * fw * A, 4))


class SSDDetector(nn.Module):
    num_classes: int = 2
    width: int = 32
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images):
        feat = Backbone(width=self.width, dtype=self.dtype)(images)
        return SSDHead(num_classes=self.num_classes,
                       dtype=self.dtype)(feat)


def decode_boxes(anchors: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Standard SSD box decode -> [y1, x1, y2, x2] unit coords, clipped to
    the image (downstream crops must never sample fully out of frame)."""
    cy = anchors[:, 0] + deltas[..., 0] * anchors[:, 2]
    cx = anchors[:, 1] + deltas[..., 1] * anchors[:, 3]
    h = anchors[:, 2] * jnp.exp(jnp.clip(deltas[..., 2], -4, 4))
    w = anchors[:, 3] * jnp.exp(jnp.clip(deltas[..., 3], -4, 4))
    boxes = jnp.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2],
                      axis=-1)
    return jnp.clip(boxes, 0.0, 1.0)


@functools.partial(jax.jit, static_argnames=("top_k",))
def batched_nms(boxes, scores, top_k: int = 32, iou_thresh: float = 0.5):
    """Greedy NMS with a fixed iteration count: selects up to top_k boxes
    per image; returns (idx, keep_scores) with -1/0 padding.  Fixed shapes
    keep the whole postprocess on-device (no host sync per frame)."""
    def one_image(b, s):
        def area(bb):
            return jnp.maximum(bb[..., 2] - bb[..., 0], 0) * \
                jnp.maximum(bb[..., 3] - bb[..., 1], 0)

        def iou(b1, b2):
            y1 = jnp.maximum(b1[0], b2[..., 0])
            x1 = jnp.maximum(b1[1], b2[..., 1])
            y2 = jnp.minimum(b1[2], b2[..., 2])
            x2 = jnp.minimum(b1[3], b2[..., 3])
            inter = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)
            return inter / jnp.maximum(area(b1) + area(b2) - inter, 1e-9)

        def step(carry, _):
            sc, sel_idx, sel_sc, i = carry
            j = jnp.argmax(sc)
            best = sc[j]
            sel_idx = sel_idx.at[i].set(jnp.where(best > 0, j, -1))
            sel_sc = sel_sc.at[i].set(jnp.maximum(best, 0))
            overl = iou(b[j], b)
            sc = jnp.where(overl > iou_thresh, -1.0, sc)
            sc = sc.at[j].set(-1.0)
            return (sc, sel_idx, sel_sc, i + 1), None

        init = (s, jnp.full((top_k,), -1, jnp.int32),
                jnp.zeros((top_k,), jnp.float32), 0)
        final, _ = jax.lax.scan(step, init, None, length=top_k)
        _sc, idx, ssc, _i = final
        return idx, ssc

    return jax.vmap(one_image)(boxes, scores)


def pack_detections(cls, deltas, anchors, score_thresh: float,
                    top_k: int = 32):
    """The shared post-head decode contract: class logits + box deltas ->
    packed (B, top_k, 6) rows [y1, x1, y2, x2, score, valid] plus the
    selected boxes (B, top_k, 4).  Every detection-family kernel
    (ObjectDetect, FaceDetect, InstanceSegment) packs through here so the
    row layout and NMS policy cannot diverge between them."""
    probs = jax.nn.softmax(cls, axis=-1)[..., 1:]  # drop background
    scores = probs.max(axis=-1)
    boxes = decode_boxes(anchors, deltas)
    idx, ssc = batched_nms(boxes, scores, top_k=top_k)
    sel = jnp.take_along_axis(boxes, jnp.maximum(idx, 0)[..., None],
                              axis=1)
    valid = ((idx >= 0) & (ssc > score_thresh)).astype(jnp.float32)
    # packed fixed shape end to end so results stay on device
    # (variable-length filtering happens at the consumer)
    packed = jnp.concatenate([sel, ssc[..., None], valid[..., None]],
                             axis=-1)
    return packed, sel


def unpack_detections(row) -> Dict[str, np.ndarray]:
    """Unpack one stored ObjectDetect/FaceDetect row — a (top_k, 6) array
    [y1, x1, y2, x2, score, valid] — into the classic
    {"boxes": (n, 4), "scores": (n,)} dict, dropping padding rows.
    Rows from tables written before the packed format (per-row dicts)
    pass through unchanged, so old committed tables stay readable."""
    if isinstance(row, dict):
        return {"boxes": np.asarray(row["boxes"], np.float32),
                "scores": np.asarray(row["scores"], np.float32)}
    a = np.asarray(row, np.float32)
    keep = a[:, 5] > 0.5
    return {"boxes": a[keep, :4], "scores": a[keep, 4]}


def anchored_cost_flops(kern, batch):
    """Shared MFU probe for the anchors-based detection family
    (ObjectDetect / FaceDetect / InstanceSegment): resolve the batch's
    stride-16 anchor grid like execute() does, then ask XLA's cost
    analysis for the jitted inference's FLOPs (infer.lowered_flops)."""
    from .infer import lowered_flops
    images = jnp.asarray(batch)
    fh = -(-images.shape[1] // 16)
    fw = -(-images.shape[2] // 16)
    if (fh, fw) not in kern._anchors:
        kern._anchors[(fh, fw)] = jnp.asarray(make_anchors(fh, fw))
    return lowered_flops(kern._infer, kern.params, images,
                         kern._anchors[(fh, fw)])


@register_op(device=DeviceType.TPU, batch=8)
class ObjectDetect(Kernel):
    """Per-frame object detections as packed (top_k, 6) rows
    [y1, x1, y2, x2, score, valid] in unit coordinates — decode with
    `unpack_detections` (reference TF SSD app equivalent).

    With no `checkpoint_dir`, width-8 instances restore the shipped
    synthetic-task weights (models/weights/detect_ssd_w8.npz, provenance
    models/detect_train.py) — like the reference app downloading SSD
    mobilenet by default; pass `pretrained=False` for random init."""

    _shipped = "detect_ssd_w8.npz"
    _shipped_width = 8

    def __init__(self, config, width: int = 32, num_classes: int = 2,
                 score_thresh: float = 0.05, seed: int = 0,
                 checkpoint_dir: Optional[str] = None,
                 pretrained: bool = True):
        super().__init__(config)
        self.model = SSDDetector(num_classes=num_classes, width=width)
        from .checkpoint import init_or_restore, shipped_weights
        if checkpoint_dir is None and pretrained \
                and width == self._shipped_width and num_classes == 2:
            checkpoint_dir = shipped_weights(self._shipped)
        self.params = init_or_restore(
            self.model, jax.random.PRNGKey(seed),
            jnp.zeros((1, 128, 128, 3), jnp.uint8), checkpoint_dir)
        self.score_thresh = float(score_thresh)
        self._anchors = {}  # (fh, fw) -> anchor tensor, per resolution

        thresh = self.score_thresh

        @jax.jit
        def infer(params, images, anchors):
            cls, deltas = self.model.apply(params, images)
            packed, _sel = pack_detections(cls, deltas, anchors, thresh)
            return packed

        self._infer = infer

    def infer_cost_flops(self, batch):
        """XLA-reported FLOPs for one inference call on `batch` (for
        the bench's MFU accounting); None when unavailable."""
        return anchored_cost_flops(self, batch)

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        """Returns a (B, top_k, 6) float32 batch — per row a (top_k, 6)
        array [y1, x1, y2, x2, score, valid] in unit coordinates,
        `valid`-padded (see unpack_detections).  Returned WITHOUT a host
        sync: device arrays chain through the column store and the sink
        fetches once per task (a per-packet fetch would serialize the
        pipeline on d2h latency, PERF.md §1)."""
        images = jnp.asarray(frame)
        # SAME-padded stride-16 backbone -> ceil-divided feature map
        fh = -(-images.shape[1] // 16)
        fw = -(-images.shape[2] // 16)
        if (fh, fw) not in self._anchors:
            self._anchors[(fh, fw)] = jnp.asarray(make_anchors(fh, fw))
        return self._infer(self.params, images, self._anchors[(fh, fw)])
