"""Train the SSD detector + embedding net on synthetic tasks and ship
checkpoints.

The reference apps load externally-trained models (object detection:
examples/apps/object_detection_tensorflow/main.py:16-23 downloads SSD
mobilenet; face detection: examples/apps/face_detection/main.py).  This
framework trains its own: fully reproducible weight provenance, the same
story as the flagship pose model (models/pose_train.py).  Three tasks:

* **ObjectDetect** — localize 1-3 bright rectangles on a noisy dark
  background (anchor-matched SSD loss).
* **FaceDetect**  — same machinery, face-like targets (bright ellipse
  with two dark "eyes"), separate weights.
* **FaceEmbedding** — identity metric learning: K procedural-texture
  identities under crop/brightness/noise augmentation, trained with a
  classification head; the shipped embedding is the L2-normalized
  projection (recall@1 asserted in tests/test_models.py).

`python -m scanner_tpu.models.detect_train <out_dir>` trains all three
and exports portable .npz weight files (models/weights/ ships them).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

# shared synthetic-task geometry (training, tests and examples agree)
SIZE = 64
WIDTH = 8
EMBED_DIM = 128
EMBED_IDENTITIES = 16


# ---------------------------------------------------------------------------
# Synthetic scenes
# ---------------------------------------------------------------------------

def render_rect_scene(rng: np.random.RandomState, size: int = SIZE,
                      max_objects: int = 3
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Noisy dark frame with 1..max_objects bright axis-aligned
    rectangles.  Returns (frame uint8 (S,S,3), boxes (N,4) unit
    [y1,x1,y2,x2])."""
    frame = rng.randint(0, 40, (size, size, 3)).astype(np.uint8)
    n = rng.randint(1, max_objects + 1)
    boxes = []
    for _ in range(n):
        h = rng.randint(10, 28)
        w = rng.randint(10, 28)
        y = rng.randint(0, size - h)
        x = rng.randint(0, size - w)
        color = rng.randint(170, 255, 3)
        frame[y:y + h, x:x + w] = color
        boxes.append([y / size, x / size, (y + h) / size, (x + w) / size])
    return frame, np.asarray(boxes, np.float32)


def render_face_scene(rng: np.random.RandomState, size: int = SIZE,
                      max_objects: int = 2
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Face-like targets: bright ellipse with two dark eye dots."""
    frame = rng.randint(0, 40, (size, size, 3)).astype(np.uint8)
    n = rng.randint(1, max_objects + 1)
    ys, xs = np.mgrid[0:size, 0:size]
    boxes = []
    for _ in range(n):
        h = rng.randint(14, 30)
        w = int(h * rng.uniform(0.7, 0.9))
        cy = rng.randint(h // 2, size - h // 2)
        cx = rng.randint(w // 2, size - w // 2)
        mask = (((ys - cy) / (h / 2)) ** 2 + ((xs - cx) / (w / 2)) ** 2) <= 1
        tone = np.array([rng.randint(190, 250), rng.randint(150, 210),
                         rng.randint(120, 180)])
        frame[mask] = tone
        for ex in (-w // 5, w // 5):  # eyes
            ey, exx = cy - h // 6, cx + ex
            frame[max(ey - 1, 0):ey + 2, max(exx - 1, 0):exx + 2] = 15
        boxes.append([(cy - h / 2) / size, (cx - w / 2) / size,
                      (cy + h / 2) / size, (cx + w / 2) / size])
    return frame, np.asarray(boxes, np.float32)


def render_identity(rng_id: int, view_rng: np.random.RandomState,
                    size: int = SIZE) -> np.ndarray:
    """One augmented view of a procedural-texture identity: the identity
    seed fixes an 8x8 color tile; views vary by shift, brightness and
    noise."""
    base_rng = np.random.RandomState(1000 + rng_id)
    tile = base_rng.randint(0, 255, (8, 8, 3)).astype(np.float32)
    img = np.kron(tile, np.ones((size // 8, size // 8, 1), np.float32))
    # augment: circular shift, brightness scale, additive noise
    sy, sx = view_rng.randint(0, size, 2)
    img = np.roll(np.roll(img, sy, axis=0), sx, axis=1)
    img = img * view_rng.uniform(0.6, 1.4)
    img = img + view_rng.normal(0, 18, img.shape)
    return np.clip(img, 0, 255).astype(np.uint8)


# ---------------------------------------------------------------------------
# SSD anchor matching (host-side numpy; targets feed the jitted loss)
# ---------------------------------------------------------------------------

def _anchor_corners(anchors: np.ndarray) -> np.ndarray:
    cy, cx, h, w = anchors.T
    return np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], 1)


def _iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(N,4) x (M,4) corner boxes -> (N,M) IoU."""
    y1 = np.maximum(a[:, None, 0], b[None, :, 0])
    x1 = np.maximum(a[:, None, 1], b[None, :, 1])
    y2 = np.minimum(a[:, None, 2], b[None, :, 2])
    x2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.clip(y2 - y1, 0, None) * np.clip(x2 - x1, 0, None)
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter,
                              1e-9)


def match_anchors(anchors: np.ndarray, gt: np.ndarray,
                  pos_iou: float = 0.5, neg_iou: float = 0.4
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """SSD target assignment.  anchors (N,4) [cy,cx,h,w]; gt (M,4)
    corners.  Returns (cls (N,) int32: 1 pos / 0 neg / -1 ignore,
    deltas (N,4) f32, zero outside positives)."""
    N = anchors.shape[0]
    cls = np.zeros((N,), np.int32)
    deltas = np.zeros((N, 4), np.float32)
    if gt.shape[0] == 0:
        return cls, deltas
    iou = _iou_matrix(_anchor_corners(anchors), gt)
    best_gt = iou.argmax(1)
    best_iou = iou.max(1)
    cls[(best_iou >= neg_iou) & (best_iou < pos_iou)] = -1
    pos = best_iou >= pos_iou
    # every gt claims its best anchor even below the threshold
    forced = iou.argmax(0)
    pos[forced] = True
    best_gt[forced] = np.arange(gt.shape[0])
    cls[pos] = 1
    g = gt[best_gt[pos]]
    gcy = (g[:, 0] + g[:, 2]) / 2
    gcx = (g[:, 1] + g[:, 3]) / 2
    gh = g[:, 2] - g[:, 0]
    gw = g[:, 3] - g[:, 1]
    a = anchors[pos]
    deltas[pos] = np.stack([
        (gcy - a[:, 0]) / a[:, 2], (gcx - a[:, 1]) / a[:, 3],
        np.log(np.maximum(gh, 1e-4) / a[:, 2]),
        np.log(np.maximum(gw, 1e-4) / a[:, 3])], 1)
    return cls, deltas


def synth_scene_video(path: str, renderer: Callable = None,
                      num_frames: int = 24, size: int = SIZE,
                      fps: float = 24.0, seed: int = 11):
    """Encode a clip of independent synthetic scenes to mp4; returns the
    per-frame ground-truth box lists.  The e2e counterpart of
    detection_batch: the exact task the shipped detector weights were
    trained on, but through the video codec path (crf 14 keeps the
    rectangles crisp enough for IoU checks)."""
    from ..video.ingest import encode_frames_mp4

    renderer = renderer or render_rect_scene
    rng = np.random.RandomState(seed)
    frames, gts = [], []
    for _ in range(num_frames):
        f, gt = renderer(rng, size)
        frames.append(f)
        gts.append(gt)
    encode_frames_mp4(path, frames, size, size, fps=fps, keyint=8, crf=14)
    return gts


def box_iou(a, b) -> float:
    """IoU of two corner boxes [y1,x1,y2,x2] (unit coords)."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return float(_iou_matrix(a[None], b[None])[0, 0])


def detection_batch(rng: np.random.RandomState, batch: int,
                    anchors: np.ndarray, renderer: Callable,
                    size: int = SIZE):
    """(frames (B,S,S,3) u8, cls (B,N) i32, deltas (B,N,4) f32)."""
    frames = np.zeros((batch, size, size, 3), np.uint8)
    N = anchors.shape[0]
    cls = np.zeros((batch, N), np.int32)
    deltas = np.zeros((batch, N, 4), np.float32)
    for b in range(batch):
        frames[b], gt = renderer(rng, size)
        cls[b], deltas[b] = match_anchors(anchors, gt)
    return frames, cls, deltas


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------

def train_detector(checkpoint_dir: str, renderer: Callable = None,
                   steps: int = 300, batch: int = 4, size: int = SIZE,
                   width: int = WIDTH, seed: int = 0,
                   export_npz: Optional[str] = None,
                   log_every: int = 50) -> float:
    """Train SSDDetector on the synthetic scene task; orbax checkpoint +
    optional portable .npz export.  Returns final loss."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..util.log import get_logger
    from .checkpoint import TrainCheckpointer, export_params_npz
    from .detection import SSDDetector, make_anchors

    log = get_logger("train")
    renderer = renderer or render_rect_scene
    fh = fw = -(-size // 16)
    anchors_np = make_anchors(fh, fw)
    anchors = jnp.asarray(anchors_np)

    model = SSDDetector(num_classes=2, width=width)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, size, size, 3), jnp.uint8))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, frames, cls_t, box_t):
        logits, deltas = model.apply(p, frames)           # (B,N,2),(B,N,4)
        valid = (cls_t >= 0)
        pos = (cls_t == 1)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.maximum(cls_t, 0))
        # balance: positives are rare among N anchors — weight them up
        w = jnp.where(pos, 10.0, 1.0) * valid
        cls_loss = (ce * w).sum() / jnp.maximum(w.sum(), 1.0)
        hub = optax.huber_loss(deltas, box_t).sum(-1)
        box_loss = (hub * pos).sum() / jnp.maximum(pos.sum(), 1.0)
        return cls_loss + box_loss

    @jax.jit
    def step_fn(p, s, frames, cls_t, box_t):
        loss, grads = jax.value_and_grad(loss_fn)(p, frames, cls_t, box_t)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    rng = np.random.RandomState(seed)
    loss = float("nan")
    for i in range(steps):
        frames, cls_t, box_t = detection_batch(rng, batch, anchors_np,
                                               renderer, size)
        params, opt_state, loss = step_fn(params, opt_state, frames,
                                          cls_t, box_t)
        if log_every and (i + 1) % log_every == 0:
            log.info("detect_train step %d/%d loss=%.5f", i + 1, steps,
                     float(loss))
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        ckpt.save(steps, params, opt_state)
    finally:
        ckpt.close()
    if export_npz:
        export_params_npz(params, export_npz)
    return float(loss)


def train_embedding(checkpoint_dir: str, steps: int = 300, batch: int = 16,
                    size: int = SIZE, width: int = WIDTH,
                    dim: int = EMBED_DIM,
                    identities: int = EMBED_IDENTITIES, seed: int = 0,
                    export_npz: Optional[str] = None,
                    log_every: int = 50) -> float:
    """Train EmbeddingNet: identity classification over procedural
    textures; the shipped weights are the backbone+projection (the
    classifier head is training-only scaffolding)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..util.log import get_logger
    from .checkpoint import TrainCheckpointer, export_params_npz
    from .face import EmbeddingNet

    log = get_logger("train")
    model = EmbeddingNet(dim=dim, width=width)
    rng_key = jax.random.PRNGKey(seed)
    params = model.init(rng_key, jnp.zeros((1, size, size, 3), jnp.uint8))
    # training-only linear classifier on the normalized embedding
    k1, _ = jax.random.split(rng_key)
    w_cls = jax.random.normal(k1, (dim, identities)) * 0.05
    opt = optax.adam(1e-3)
    opt_state = opt.init((params, w_cls))

    def loss_fn(state, frames, labels):
        p, w = state
        emb = model.apply(p, frames)                  # (B, dim) normalized
        logits = emb @ w * 10.0                       # temperature
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    @jax.jit
    def step_fn(state, s, frames, labels):
        loss, grads = jax.value_and_grad(loss_fn)(state, frames, labels)
        updates, s = opt.update(grads, s, state)
        return optax.apply_updates(state, updates), s, loss

    rng = np.random.RandomState(seed)
    state = (params, w_cls)
    loss = float("nan")
    for i in range(steps):
        labels = rng.randint(0, identities, batch)
        frames = np.stack([render_identity(l, rng, size) for l in labels])
        state, opt_state, loss = step_fn(state, opt_state, frames,
                                         labels.astype(np.int32))
        if log_every and (i + 1) % log_every == 0:
            log.info("embed_train step %d/%d loss=%.5f", i + 1, steps,
                     float(loss))
    params = state[0]
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        ckpt.save(steps, params, opt_state)
    finally:
        ckpt.close()
    if export_npz:
        export_params_npz(params, export_npz)
    return float(loss)


def main(argv: Optional[list] = None) -> None:
    import argparse
    import os

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out_dir")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--which", default="all",
                    choices=["all", "detect", "face", "embed"])
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (an ambient accelerator "
                    "plugin can override JAX_PLATFORMS at config level; "
                    "this forces it before the first backend touch)")
    args = ap.parse_args(argv)
    if args.cpu:
        from ..util.jaxenv import force_cpu_platform
        force_cpu_platform()
    os.makedirs(args.out_dir, exist_ok=True)
    if args.which in ("all", "detect"):
        loss = train_detector(
            os.path.join(args.out_dir, "detect_ckpt"),
            render_rect_scene, steps=args.steps, seed=0,
            export_npz=os.path.join(args.out_dir,
                                    f"detect_ssd_w{WIDTH}.npz"))
        print(f"detect: final loss {loss:.5f}")
    if args.which in ("all", "face"):
        loss = train_detector(
            os.path.join(args.out_dir, "face_ckpt"),
            render_face_scene, steps=args.steps, seed=1,
            export_npz=os.path.join(args.out_dir,
                                    f"face_ssd_w{WIDTH}.npz"))
        print(f"face: final loss {loss:.5f}")
    if args.which in ("all", "embed"):
        loss = train_embedding(
            os.path.join(args.out_dir, "embed_ckpt"), steps=args.steps,
            seed=2,
            export_npz=os.path.join(args.out_dir,
                                    f"embed_w{WIDTH}.npz"))
        print(f"embed: final loss {loss:.5f}")


if __name__ == "__main__":
    main()
