"""Training-state checkpointing (orbax).

The reference's checkpoint story is job-level (committed tables +
CacheMode.Ignore resume — SURVEY §5); model *training* is new in this
framework, so its state gets first-class checkpointing: params + optimizer
state + step, sharding-aware via orbax (restores onto the current mesh).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, params: Any, opt_state: Any) -> None:
        self._mgr.save(step, args=ocp.args.Composite(
            params=ocp.args.StandardSave(params),
            opt_state=ocp.args.StandardSave(opt_state)))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, params_template: Any, opt_state_template: Any,
                step: Optional[int] = None) -> Tuple[Any, Any, int]:
        """Restore onto the templates' shardings (device_put'd trees)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        restored = self._mgr.restore(step, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(params_template),
            opt_state=ocp.args.StandardRestore(opt_state_template)))
        return restored["params"], restored["opt_state"], step

    def restore_params(self, params_template: Any,
                       step: Optional[int] = None) -> Any:
        """Restore params only (inference-side: kernels don't carry
        optimizer state)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        restored = self._mgr.restore(step, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(params_template)))
        return restored["params"]

    def close(self) -> None:
        self._mgr.close()


def load_params(directory: str, params_template: Any,
                step: Optional[int] = None) -> Any:
    """One-shot param restore for inference kernels
    (PoseDetect(checkpoint_dir=...) and friends).  Accepts either an
    orbax checkpoint directory or an exported .npz weight file."""
    if directory.endswith(".npz"):
        return import_params_npz(directory, params_template)
    if not os.path.isdir(directory):
        # pure read path: never create an empty orbax tree at a typo'd
        # location
        raise FileNotFoundError(f"no checkpoint directory: {directory}")
    ckpt = TrainCheckpointer(directory)
    try:
        return ckpt.restore_params(params_template, step=step)
    finally:
        ckpt.close()


def _flat_key(keypath) -> str:
    """Keypath -> the '/'-joined name used as the on-disk .npz key (the
    exported weight-file contract; export and import must agree)."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in keypath)


def init_or_restore(model, rng, dummy_input, checkpoint_dir: Optional[str]):
    """The inference-kernel weight path: with a checkpoint, build the
    restore template abstractly (jax.eval_shape — no init compute) and
    device_put the restored tree so execute() never re-uploads weights;
    without one, plain random init."""
    if checkpoint_dir:
        template = jax.eval_shape(model.init, rng, dummy_input)
        return jax.device_put(load_params(checkpoint_dir, template))
    return model.init(rng, dummy_input)


def shipped_weights(filename: str) -> Optional[str]:
    """Path of a weight file shipped in models/weights/, or None.

    Model kernels default to shipped trained weights when the caller gives
    no checkpoint and the requested width matches the shipped
    configuration (the reference apps likewise download pretrained models
    by default, object_detection_tensorflow/main.py:16-23)."""
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "weights", filename)
    return p if os.path.exists(p) else None


def export_params_npz(params: Any, path: str) -> None:
    """Flatten a param tree into one portable .npz (the shippable weight
    format — orbax trees are for resumable TRAINING state)."""
    import numpy as np

    flat = {}
    for kp, x in jax.tree_util.tree_flatten_with_path(params)[0]:
        flat[_flat_key(kp)] = np.asarray(x)
    np.savez_compressed(path, **flat)


def import_params_npz(path: str, params_template: Any) -> Any:
    """Rebuild a param tree from an exported .npz using the template's
    structure; shapes must match the template's configuration."""
    import numpy as np

    with np.load(path) as data:
        flat = dict(data)
    leaves_kp, treedef = jax.tree_util.tree_flatten_with_path(
        params_template)
    leaves = []
    for kp, tmpl in leaves_kp:
        key = _flat_key(kp)
        if key not in flat:
            raise KeyError(f"weight file {path} missing parameter {key}")
        arr = flat[key]
        if tuple(arr.shape) != tuple(tmpl.shape):
            raise ValueError(
                f"{key}: weight shape {arr.shape} != template "
                f"{tuple(tmpl.shape)} (width mismatch?)")
        leaves.append(arr.astype(tmpl.dtype))
    return treedef.unflatten(leaves)
