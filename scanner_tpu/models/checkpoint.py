"""Training-state checkpointing (orbax).

The reference's checkpoint story is job-level (committed tables +
CacheMode.Ignore resume — SURVEY §5); model *training* is new in this
framework, so its state gets first-class checkpointing: params + optimizer
state + step, sharding-aware via orbax (restores onto the current mesh).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, params: Any, opt_state: Any) -> None:
        self._mgr.save(step, args=ocp.args.Composite(
            params=ocp.args.StandardSave(params),
            opt_state=ocp.args.StandardSave(opt_state)))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, params_template: Any, opt_state_template: Any,
                step: Optional[int] = None) -> Tuple[Any, Any, int]:
        """Restore onto the templates' shardings (device_put'd trees)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        restored = self._mgr.restore(step, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(params_template),
            opt_state=ocp.args.StandardRestore(opt_state_template)))
        return restored["params"], restored["opt_state"], step

    def close(self) -> None:
        self._mgr.close()
