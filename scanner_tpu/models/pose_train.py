"""Train VideoPoseNet on a synthetic keypoint task and ship a checkpoint.

The reference pose app wraps externally-trained OpenPose weights
(examples/apps/pose_detection/main.py:50-56).  This framework trains its
own flagship model; the synthetic task — localize a bright moving blob in
a noisy clip — gives a fully reproducible weight-provenance story: a few
hundred steps on one chip produce a checkpoint whose keypoint-0 heatmap
demonstrably localizes the target, which `PoseDetect(checkpoint_dir=...)`
then restores for inference inside engine pipelines.

`python -m scanner_tpu.models.pose_train <ckpt_dir>` trains the default
configuration; `train_pose()` is the library entry.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from .pose import NUM_KEYPOINTS, init_params, make_train_step

# default synthetic-task geometry (kernel/test/example all share it)
SIZE = 48
WIDTH = 8


def render_blob_frame(h: int, w: int, cx: float, cy: float,
                      rng: np.random.RandomState,
                      radius: float = 4.0) -> np.ndarray:
    """Noisy dark frame with a bright Gaussian blob at (cx, cy)."""
    ys, xs = np.mgrid[0:h, 0:w]
    blob = np.exp(-(((xs - cx) ** 2 + (ys - cy) ** 2)
                    / (2.0 * radius ** 2)))
    base = rng.randint(0, 40, (h, w, 3)).astype(np.float32)
    frame = base + 215.0 * blob[..., None]
    return np.clip(frame, 0, 255).astype(np.uint8)


def heatmap_target(h: int, w: int, cx: float, cy: float,
                   sigma: float = 1.5) -> np.ndarray:
    """(h, w, K) target: keypoint 0 gets a Gaussian at (cx, cy) in
    heatmap coords; the remaining keypoints are empty."""
    ys, xs = np.mgrid[0:h, 0:w]
    g = np.exp(-(((xs - cx) ** 2 + (ys - cy) ** 2) / (2.0 * sigma ** 2)))
    out = np.zeros((h, w, NUM_KEYPOINTS), np.float32)
    out[..., 0] = g
    return out


def synth_batch(rng: np.random.RandomState, batch: int, time: int,
                size: int = SIZE) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """Clips with a blob moving on a straight line; returns
    (clips (B,T,H,W,3) uint8, targets (B,T,H/4,W/4,K) f32,
    centers (B,T,2) [cx, cy] in frame coords)."""
    hm = size // 4
    clips = np.zeros((batch, time, size, size, 3), np.uint8)
    targets = np.zeros((batch, time, hm, hm, NUM_KEYPOINTS), np.float32)
    centers = np.zeros((batch, time, 2), np.float32)
    margin = 8
    for b in range(batch):
        x0, y0 = rng.uniform(margin, size - margin, 2)
        ang = rng.uniform(0, 2 * math.pi)
        step = rng.uniform(0.5, 2.5)
        for t in range(time):
            cx = float(np.clip(x0 + t * step * math.cos(ang),
                               margin / 2, size - margin / 2))
            cy = float(np.clip(y0 + t * step * math.sin(ang),
                               margin / 2, size - margin / 2))
            clips[b, t] = render_blob_frame(size, size, cx, cy, rng)
            targets[b, t] = heatmap_target(hm, hm, cx / 4.0, cy / 4.0)
            centers[b, t] = (cx, cy)
    return clips, targets, centers


def synth_blob_video(path: str, num_frames: int = 24, size: int = SIZE,
                     fps: float = 24.0, seed: int = 7) -> np.ndarray:
    """Encode a blob-motion clip to mp4; returns (num_frames, 2) true
    centers.  The e2e counterpart of synth_batch: the same task the
    shipped weights were trained on, but through the video codec path."""
    from ..video.ingest import encode_frames_mp4

    rng = np.random.RandomState(seed)
    margin = 8
    x0, y0 = rng.uniform(margin, size - margin, 2)
    ang = rng.uniform(0, 2 * math.pi)
    step = rng.uniform(0.8, 1.6)
    centers = np.zeros((num_frames, 2), np.float32)
    frames = []
    for t in range(num_frames):
        cx = float(np.clip(x0 + t * step * math.cos(ang),
                           margin / 2, size - margin / 2))
        cy = float(np.clip(y0 + t * step * math.sin(ang),
                           margin / 2, size - margin / 2))
        centers[t] = (cx, cy)
        frames.append(render_blob_frame(size, size, cx, cy, rng))
    encode_frames_mp4(path, frames, size, size, fps=fps, keyint=8, crf=16)
    return centers


def train_pose(checkpoint_dir: str, steps: int = 300, batch: int = 4,
               time: int = 2, size: int = SIZE, width: int = WIDTH,
               seed: int = 0, log_every: int = 50) -> float:
    """Train on the synthetic task and save a checkpoint; returns the
    final loss.  Small enough to run in ~a minute on one chip/core."""
    import jax

    from ..util.log import get_logger
    from .checkpoint import TrainCheckpointer

    log = get_logger("train")
    model, params = init_params(
        jax.random.PRNGKey(seed),
        clip_shape=(1, time, size, size, 3), width=width)
    opt, step_fn = make_train_step(model)
    opt_state = opt.init(params)
    jit_step = jax.jit(step_fn, donate_argnums=(0, 1))

    rng = np.random.RandomState(seed)
    loss = float("nan")
    for i in range(steps):
        clips, targets, _ = synth_batch(rng, batch, time, size)
        params, opt_state, loss = jit_step(params, opt_state, clips,
                                           targets)
        if log_every and (i + 1) % log_every == 0:
            log.info("pose_train step %d/%d loss=%.5f", i + 1, steps,
                     float(loss))
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        ckpt.save(steps, params, opt_state)
    finally:
        ckpt.close()
    return float(loss)


def main(argv: Optional[list] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint_dir")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--width", type=int, default=WIDTH)
    ap.add_argument("--size", type=int, default=SIZE)
    args = ap.parse_args(argv)
    loss = train_pose(args.checkpoint_dir, steps=args.steps,
                      width=args.width, size=args.size)
    print(f"trained {args.steps} steps, final loss {loss:.5f}, "
          f"checkpoint at {args.checkpoint_dir}")


if __name__ == "__main__":
    main()
