# Importing registers the model ops (PoseDetect, ObjectDetect, FaceDetect,
# FaceEmbedding) — the analogue of the reference's scannertools model zoo.
from . import detection, face, pose, segmentation  # noqa: F401
from .detection import unpack_detections
from .pose import (VideoPoseNet, init_params, make_sharded_train_step,
                   make_train_step, plain_params_to_pp, pp_params_to_plain)
from .segmentation import paste_masks, unpack_instances

__all__ = ["VideoPoseNet", "init_params", "make_sharded_train_step",
           "make_train_step", "detection", "face", "pose", "segmentation",
           "unpack_detections", "unpack_instances", "paste_masks",
           "pp_params_to_plain", "plain_params_to_pp"]
