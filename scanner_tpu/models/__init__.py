# Importing registers the model ops (PoseDetect, ObjectDetect, FaceDetect,
# FaceEmbedding) — the analogue of the reference's scannertools model zoo.
from . import detection, face, pose  # noqa: F401
from .detection import unpack_detections
from .pose import (VideoPoseNet, init_params, make_sharded_train_step,
                   make_train_step)

__all__ = ["VideoPoseNet", "init_params", "make_sharded_train_step",
           "make_train_step", "detection", "face", "pose",
           "unpack_detections"]
