"""Multi-chip data-parallel inference for engine kernels.

A TPU host has several chips; an engine worker's model kernels should use
all of them.  The engine hands each kernel its visible device list
(KernelConfig.devices); `DataParallelApply` replicates the params across
those chips ONCE and dp-shards each batch's leading axis, letting GSPMD
run the jitted apply across chips with no code changes in the model
(reference kernels instead pinned one GPU per kernel instance via
KernelConfig.devices, kernel.h — on TPU one instance drives the whole
host's chips).

Uneven batches (a task's trailing partial work packet) are zero-padded to
a multiple of the device count so the sharded path — and its compiled
program — is reused, then the padding rows are sliced off the result.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np


def lowered_flops(jitfn, *args):
    """XLA cost-analysis FLOPs of one call of a jitted function (None if
    the backend/compiler does not report them).  Drives the bench's MFU
    column: achieved FLOP/s vs the chip's peak."""
    try:
        ca = jitfn.lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # noqa: BLE001
        return None


class DataParallelApply:
    """Wraps a jitted `apply(params, batch)` with per-host dp sharding."""

    def __init__(self, apply_fn, params, devices: Optional[Sequence] = None):
        self._apply = apply_fn
        self.devices = list(devices or [])
        if len(self.devices) > 1:
            import jax
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)
            self._mesh = Mesh(np.array(self.devices), ("dp",))
            self._data_sharding = NamedSharding(self._mesh, P("dp"))
            # params live replicated on every chip from construction on;
            # execute() never re-uploads them
            self.params = jax.device_put(
                params, NamedSharding(self._mesh, P()))
        else:
            self._mesh = None
            self.params = params

    def cost_flops(self, *args):
        """XLA cost-analysis FLOPs of one apply() call on `args`."""
        return lowered_flops(self._apply, self.params, *args)

    def __call__(self, batch):
        if self._mesh is None or len(batch) == 0:
            return self._apply(self.params, batch)
        import jax
        import jax.numpy as jnp

        n = len(self.devices)
        rows = len(batch)
        pad = (-rows) % n
        if pad:
            batch = jnp.concatenate(
                [jnp.asarray(batch),
                 jnp.zeros((pad,) + tuple(batch.shape[1:]),
                           batch.dtype)])
        batch = jax.device_put(batch, self._data_sharding)
        out = self._apply(self.params, batch)
        if pad:
            out = jax.tree_util.tree_map(lambda x: x[:rows], out)
        return out
