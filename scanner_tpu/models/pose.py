"""Pose estimation: the flagship model family.

Capability parity: reference examples/apps/pose_detection (OpenPose Caffe
kernel, main.py:50-56) — rebuilt as a TPU-native video pose network:
per-frame conv backbone -> temporal attention over the clip (ring attention
when the time axis is sharded over 'sp') -> MoE mixer -> deconv heatmap
head.  The train step shards dp (batch), sp (time), tp (channels/experts)
over one jax Mesh; XLA inserts all collectives.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op
from .nets import Backbone, DeconvHead, TemporalBlock

NUM_KEYPOINTS = 17


class PipelinedTemporalStack(nn.Module):
    """The temporal trunk as an in-program pipeline: one TemporalBlock's
    parameter structure repeated `num_stages` times, stacked on a leading
    axis sharded over the mesh's 'pp' ranks, executed with the GPipe
    microbatch schedule (parallel/pp.py).  Each pp rank holds exactly one
    stage's weights — the HBM-scaling path when the trunk outgrows a
    chip.  Stages are collective-free, so sp must be 1 (dp/tp compose)."""

    mesh: Any
    num_stages: int
    num_microbatches: int = 2
    dtype: Any = jnp.bfloat16
    # forwarded to every stage's TemporalBlock; must be collective-free
    # (stages run inside shard_map — a mesh-collective attention like
    # ring/ulysses cannot nest here, which is why pp requires sp == 1)
    attn_fn: Optional[Any] = None
    # jax.checkpoint each stage call (pp is the HBM-constrained case, so
    # the trunk must honor remat like the in-module stack does)
    remat: bool = False

    @nn.compact
    def __call__(self, tokens):
        from ..parallel.pp import make_pipeline, stack_stage_params

        blk = TemporalBlock(dtype=self.dtype, attn_fn=self.attn_fn)

        def init_stages(rng):
            keys = jax.random.split(rng, self.num_stages)
            return stack_stage_params(
                [blk.init(k, tokens[:1]) for k in keys])

        stacked = self.param("stages", init_stages)
        if self.is_initializing():
            # init only creates params; the schedule needs the real
            # (dp-sharded, microbatchable) batch geometry — run one stage
            # unpipelined for output shape/dtype
            return blk.apply(
                jax.tree_util.tree_map(lambda a: a[0], stacked), tokens)
        stage = lambda p, x: blk.apply(p, x)  # noqa: E731
        if self.remat:
            stage = jax.checkpoint(stage)
        pipe = make_pipeline(self.mesh, stage,
                             num_microbatches=self.num_microbatches)
        return pipe(stacked, tokens)


class VideoPoseNet(nn.Module):
    """(B, T, H, W, 3) uint8 clip -> (B, T, H/4, W/4, K) heatmaps."""

    width: int = 32
    temporal_layers: int = 2
    keypoints: int = NUM_KEYPOINTS
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Any] = None
    # a mesh with a 'pp' axis pipelines the temporal trunk over its
    # stages (PipelinedTemporalStack); None keeps the in-module stack
    pipeline_mesh: Optional[Any] = None
    pipeline_microbatches: int = 2
    # rematerialize the backbone + temporal blocks on the backward pass
    # (jax.checkpoint): activations of the deepest trunk are recomputed
    # instead of stored — the HBM/FLOPs trade for long clips at high
    # resolution.  Same math: losses/grads match the unremat'd model.
    remat: bool = False

    @nn.compact
    def __call__(self, clip):
        B, T, H, W, _ = clip.shape
        frames = clip.reshape(B * T, H, W, 3)
        # explicit names pin the param tree to the unremat'd layout, so
        # remat toggles freely over the same weights (incl. shipped .npz)
        BackboneM = nn.remat(Backbone) if self.remat else Backbone
        feat = BackboneM(width=self.width, dtype=self.dtype,
                         name="Backbone_0")(frames)
        _, fh, fw, C = feat.shape
        # clip-level context: GAP tokens mixed across time
        tokens = feat.mean(axis=(1, 2)).reshape(B, T, C)
        if self.pipeline_mesh is not None:
            tokens = PipelinedTemporalStack(
                mesh=self.pipeline_mesh,
                num_stages=self.temporal_layers,
                num_microbatches=self.pipeline_microbatches,
                dtype=self.dtype, attn_fn=self.attn_fn,
                remat=self.remat)(tokens)
        else:
            BlockM = nn.remat(TemporalBlock) if self.remat \
                else TemporalBlock
            for li in range(self.temporal_layers):
                tokens = BlockM(dtype=self.dtype, attn_fn=self.attn_fn,
                                name=f"TemporalBlock_{li}")(tokens)
        # FiLM-style broadcast of temporal context back onto spatial maps
        scale = nn.Dense(C, dtype=self.dtype, name="film")(tokens)
        feat = feat.reshape(B, T, fh, fw, C)
        feat = feat * (1.0 + scale[:, :, None, None, :])
        heat = DeconvHead(keypoints=self.keypoints,
                          dtype=self.dtype)(feat.reshape(B * T, fh, fw, C))
        return heat.reshape(B, T, heat.shape[1], heat.shape[2],
                            self.keypoints)


def init_params(rng, clip_shape=(1, 4, 128, 128, 3), **kw):
    model = VideoPoseNet(**kw)
    clip = jnp.zeros(clip_shape, jnp.uint8)
    return model, model.init(rng, clip)


def pp_params_to_plain(params):
    """Convert a pipeline-mesh VideoPoseNet param tree (stacked stages
    under PipelinedTemporalStack_0/stages) to the plain serving layout
    (TemporalBlock_i) — train with pp, serve with the engine kernels.
    The schedule is exactly the sequential composition (parallel/pp.py),
    so converted params produce identical outputs."""
    p = dict(params["params"])
    if "PipelinedTemporalStack_0" not in p:
        return params  # already plain
    stacked = p.pop("PipelinedTemporalStack_0")["stages"]["params"]
    leaves = jax.tree_util.tree_leaves(stacked)
    S = int(leaves[0].shape[0])
    for i in range(S):
        p[f"TemporalBlock_{i}"] = jax.tree_util.tree_map(
            lambda a, i=i: np.asarray(a[i]), stacked)
    return {"params": p}


def plain_params_to_pp(params):
    """Inverse of pp_params_to_plain: stack TemporalBlock_0..S-1 (count
    derived from the tree) into the pipeline layout so plain-trained (or
    shipped) weights can continue training on a pp mesh."""
    from ..parallel.pp import stack_stage_params

    p = dict(params["params"])
    if "PipelinedTemporalStack_0" in p:
        return params  # already pipelined
    blocks = []
    while f"TemporalBlock_{len(blocks)}" in p:
        blocks.append(p.pop(f"TemporalBlock_{len(blocks)}"))
    if not blocks:
        raise ValueError("no TemporalBlock_i entries to stack")
    p["PipelinedTemporalStack_0"] = {
        "stages": {"params": stack_stage_params(blocks)}}
    return {"params": p}


def param_shardings(params, mesh: Mesh):
    """tp-shard the big tensors: dense/conv kernels on their output
    channel, MoE expert tensors on the expert dim — over a dedicated
    'ep' axis when the mesh has one, else folded onto 'tp'; pipelined
    stage stacks on 'pp'; everything else replicated.  GSPMD propagates
    the rest (per-expert matmuls shard with their weights; the routed
    sum over experts becomes an all-reduce over the expert axis)."""
    has_pp = "pp" in mesh.axis_names and mesh.shape["pp"] > 1
    expert_axis = "ep" if ("ep" in mesh.axis_names
                           and mesh.shape["ep"] > 1) else "tp"

    def spec_for(path, x):
        name = "/".join(str(p.key) for p in path
                        if hasattr(p, "key"))
        if has_pp and "stages" in name:
            # pipeline stages: each pp rank holds its own stage's weights
            return NamedSharding(
                mesh, P(*(("pp",) + (None,) * (x.ndim - 1))))
        if ("w1" in name or "w2" in name) and x.ndim == 3 \
                and x.shape[0] % mesh.shape[expert_axis] == 0:
            return NamedSharding(mesh, P(expert_axis, None, None))
        if x.ndim == 2 and x.shape[1] % mesh.shape["tp"] == 0:
            return NamedSharding(mesh, P(None, "tp"))
        if x.ndim == 4 and x.shape[3] % mesh.shape["tp"] == 0:
            return NamedSharding(mesh, P(None, None, None, "tp"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def make_train_step(model: VideoPoseNet, optimizer=None):
    opt = optimizer or optax.adam(1e-3)

    def loss_fn(params, clip, target):
        heat = model.apply(params, clip)
        return jnp.mean((heat - target) ** 2)

    def train_step(params, opt_state, clip, target):
        loss, grads = jax.value_and_grad(loss_fn)(params, clip, target)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return opt, train_step


def make_sharded_train_step(mesh: Mesh, clip_shape=(8, 8, 64, 64, 3),
                            width: int = 32,
                            attn_scheme: Optional[str] = None,
                            remat: bool = False,
                            pipeline_microbatches: int = 2,
                            temporal_layers: Optional[int] = None):
    """Build the full multi-chip training step: dp-sharded batch,
    sp-sharded time (ring attention), tp-sharded params/experts.
    Returns (jitted_step, params, opt_state, example batch).

    attn_scheme selects the sequence-parallel attention: "ring"
    (default), "pallas" (ring with the fused pallas flash kernel,
    kernels/pallas_attention.py), or "ulysses" (all-to-all head
    sharding); None reads SCANNER_TPU_ATTN (same values).

    A mesh with a 'pp' axis > 1 pipelines the temporal trunk over its
    stages (PipelinedTemporalStack / parallel/pp.py).  Pipeline stages
    are collective-free, so pp requires sp == 1 (dp and tp compose).
    `pipeline_microbatches` (M) sets the schedule's bubble fraction
    (S-1)/(M+S-1); the per-dp-shard batch must divide by M.  remat=True
    wraps backbone + temporal blocks (incl. pipeline stages) in
    jax.checkpoint — recompute activations instead of storing them.

    On a pp mesh the temporal-trunk depth IS the pipeline depth: one
    temporal block per stage.  Pass `temporal_layers` to assert the
    depth you expect — a mismatch with the pp axis size raises instead
    of silently changing the architecture with the mesh."""
    import os

    attn = None
    pp = int(mesh.shape.get("pp", 1)) if "pp" in mesh.axis_names else 1
    if pp > 1 and mesh.shape["sp"] > 1:
        raise ValueError(
            "pp > 1 requires sp == 1: pipeline stages are "
            "collective-free, so sequence-parallel attention cannot run "
            "inside a stage")
    if mesh.shape["sp"] > 1:
        scheme = attn_scheme or os.environ.get("SCANNER_TPU_ATTN", "ring")
        if scheme not in ("ring", "pallas", "ulysses"):
            raise ValueError(
                f"unknown attention scheme {scheme!r}; expected "
                "'ring', 'pallas' or 'ulysses'")
        if scheme == "ulysses":
            from ..parallel.ulysses import make_ulysses_attention
            attn = make_ulysses_attention(mesh, axis="sp")
        else:
            from ..parallel.ring_attention import make_ring_attention
            attn = make_ring_attention(
                mesh, axis="sp",
                impl="pallas" if scheme == "pallas" else "xla")
    kw = {"remat": remat}
    if pp > 1:
        if temporal_layers is not None and temporal_layers != pp:
            raise ValueError(
                f"temporal_layers={temporal_layers} but the mesh's pp axis "
                f"has {pp} stages; the pipelined trunk runs exactly one "
                "temporal block per stage, so the two must be equal "
                "(resize the pp axis or drop the argument)")
        kw.update(pipeline_mesh=mesh, temporal_layers=pp,
                  pipeline_microbatches=pipeline_microbatches)
    elif temporal_layers is not None:
        kw.update(temporal_layers=temporal_layers)
    model, params = init_params(
        jax.random.PRNGKey(0),
        clip_shape=(1,) + tuple(clip_shape[1:]), width=width,
        attn_fn=attn, **kw)
    opt, step = make_train_step(model)
    p_shard = param_shardings(params, mesh)
    params = jax.device_put(params, p_shard)
    opt_state = opt.init(params)
    data_spec = NamedSharding(mesh, P("dp", "sp"))
    B, T = clip_shape[0], clip_shape[1]
    hm_h, hm_w = clip_shape[2] // 4, clip_shape[3] // 4
    # deterministic nonzero data so the step exercises real numerics
    clip = jax.device_put(
        (np.arange(np.prod(clip_shape)) % 251).astype(np.uint8)
        .reshape(clip_shape), data_spec)
    tshape = (B, T, hm_h, hm_w, NUM_KEYPOINTS)
    target = jax.device_put(
        np.sin(np.arange(np.prod(tshape))).astype(np.float32)
        .reshape(tshape), data_spec)
    jit_step = jax.jit(step, donate_argnums=(0, 1))
    return jit_step, params, opt_state, (clip, target)


# ---------------------------------------------------------------------------
# Engine op
# ---------------------------------------------------------------------------

def heatmaps_to_keypoints(heat: np.ndarray) -> np.ndarray:
    """(h, w, K) heatmaps -> (K, 3) [x, y, score] in heatmap coords."""
    h, w, K = heat.shape
    flat = heat.reshape(-1, K)
    idx = flat.argmax(axis=0)
    scores = flat[idx, np.arange(K)]
    ys, xs = np.divmod(idx, w)
    return np.stack([xs, ys, scores], axis=1).astype(np.float32)


@register_op(device=DeviceType.TPU, batch=8)
class PoseDetect(Kernel):
    """Per-frame pose keypoints (reference pose_detection app op).

    With `checkpoint_dir=` the kernel restores trained weights (the
    reference app loads external OpenPose weights, main.py:50-56; here
    the provenance is scanner_tpu.models.pose_train).  `width` must
    match the trained configuration."""

    _shipped = "pose_blobnet_w8.npz"
    _shipped_width = 8

    def __init__(self, config, width: int = 32, seed: int = 0,
                 checkpoint_dir: Optional[str] = None,
                 pretrained: bool = True):
        super().__init__(config)
        from .checkpoint import init_or_restore, shipped_weights
        from .infer import DataParallelApply
        self.model = VideoPoseNet(width=width)
        if checkpoint_dir is None and pretrained \
                and width == self._shipped_width:
            checkpoint_dir = shipped_weights(self._shipped)

        def apply_and_peaks(params, clip):
            """Forward + on-device argmax: ship (B,K,3) keypoints off the
            chip, not (B,h,w,K) heatmaps — heatmaps are ~MBs per batch
            and the d2h hop is latency-bound (PERF.md §1)."""
            heat = self.model.apply(params, clip)[:, 0]   # (B, h, w, K)
            B, h, w, K = heat.shape
            flat = heat.reshape(B, h * w, K)
            idx = flat.argmax(axis=1)                     # (B, K)
            scores = jnp.take_along_axis(flat, idx[:, None, :],
                                         axis=1)[:, 0, :]
            ys, xs = idx // w, idx % w
            return jnp.stack([xs.astype(jnp.float32),
                              ys.astype(jnp.float32), scores], axis=-1)

        params = init_or_restore(
            self.model, jax.random.PRNGKey(seed),
            jnp.zeros((1, 1, 128, 128, 3), jnp.uint8), checkpoint_dir)
        # dp-shard batches over every chip the engine handed this kernel
        self._dp = DataParallelApply(jax.jit(apply_and_peaks), params,
                                     config.devices)
        self.params = self._dp.params

    def infer_cost_flops(self, batch):
        """XLA-reported FLOPs for one inference call on `batch` (for
        the bench's MFU accounting); None when unavailable."""
        return self._dp.cost_flops(jnp.asarray(batch)[:, None])

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        clip = jnp.asarray(frame)[:, None]  # (B, 1, H, W, 3)
        # (B, K, 3) [x, y, score] in heatmap coords, returned WITHOUT a
        # host sync: the column store chains device arrays and the sink
        # fetches once per task
        return self._dp(clip)
