"""Neural building blocks (flax.linen) shared by the model families.

Capability parity: the reference delegates model compute to Caffe2/TF GPU
kernels inside ops (OpenPose pose app, TF SSD detection app — SURVEY §2.4);
here models are first-class JAX modules the kernel stdlib wraps.  bfloat16
activations by default: matmuls/convs land on the MXU.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class ResBlock(nn.Module):
    ch: int
    stride: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.ch, (3, 3), strides=(self.stride, self.stride),
                    dtype=self.dtype, padding="SAME")(x)
        h = nn.GroupNorm(num_groups=8, dtype=self.dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(self.ch, (3, 3), dtype=self.dtype, padding="SAME")(h)
        h = nn.GroupNorm(num_groups=8, dtype=self.dtype)(h)
        if x.shape[-1] != self.ch or self.stride != 1:
            x = nn.Conv(self.ch, (1, 1),
                        strides=(self.stride, self.stride),
                        dtype=self.dtype)(x)
        return nn.relu(x + h)


class Backbone(nn.Module):
    """ResNet-lite feature extractor: (B, H, W, 3) -> (B, H/16, W/16, C).

    Stands in for the reference apps' ResNet/VGG backbones (pose app
    Caffe model, SSD mobilenet) in a TPU-native dress.
    """

    width: int = 64
    depths: Sequence[int] = (2, 2, 2)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype) / 255.0
        x = nn.Conv(self.width, (7, 7), strides=(4, 4), dtype=self.dtype,
                    padding="SAME")(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        x = nn.relu(x)
        ch = self.width
        for stage, depth in enumerate(self.depths):
            for i in range(depth):
                stride = 2 if (i == 0 and stage > 0) else 1
                x = ResBlock(ch, stride=stride, dtype=self.dtype)(x)
            ch *= 2
        return x  # (B, H/16, W/16, width * 2^(len(depths)-1))


class MoEMlp(nn.Module):
    """Top-1 routed mixture-of-experts MLP over tokens (B, T, C).

    Experts evaluate densely and the router's one-hot selects — compiler
    friendly (no dynamic gather), fine for small expert counts; gives the
    framework a real expert-parallel surface (experts shard over 'tp').
    """

    num_experts: int = 4
    hidden: int = 256
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        gate = nn.Dense(self.num_experts, dtype=self.dtype, name="router")(x)
        probs = jax.nn.softmax(gate.astype(jnp.float32), axis=-1)
        sel = jax.nn.one_hot(jnp.argmax(probs, -1), self.num_experts,
                             dtype=x.dtype)
        # experts as one batched params tensor: (E, C, H) and (E, H, C)
        C = x.shape[-1]
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (self.num_experts, C, self.hidden)).astype(self.dtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (self.num_experts, self.hidden, C)).astype(self.dtype)
        h = jnp.einsum("btc,ech->bteh", x, w1)
        h = nn.relu(h)
        y = jnp.einsum("bteh,ehc->btec", h, w2)
        return jnp.einsum("btec,bte->btc", y, sel)


class TemporalBlock(nn.Module):
    """Pre-norm MHA + MoE-MLP over the time axis of (B, T, C) tokens.

    attn_fn lets callers swap in ring attention (sequence sharded over the
    'sp' mesh axis) without changing the module."""

    heads: int = 4
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        D = C // self.heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * C, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv.reshape(B, T, 3 * self.heads, D), 3, axis=2)
        if self.attn_fn is not None:
            att = self.attn_fn(q, k, v)
        else:
            from ..parallel.ring_attention import reference_attention
            att = reference_attention(q, k, v)
        att = att.reshape(B, T, C)
        x = x + nn.Dense(C, dtype=self.dtype, name="proj")(att)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        return x + MoEMlp(dtype=self.dtype)(h)


class DeconvHead(nn.Module):
    """SimpleBaseline-style upsampling head producing K heatmaps."""

    keypoints: int = 17
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.ConvTranspose(128, (4, 4), strides=(2, 2),
                                 dtype=self.dtype)(x)
            x = nn.relu(x)
        return nn.Conv(self.keypoints, (1, 1), dtype=jnp.float32)(x)
