"""scanner_tpu: a TPU-native framework for efficient analysis of large video
datasets.

Capabilities mirror scanner-research/scanner (SIGGRAPH 2018): computation
graphs (Source -> Ops -> Sink) over tables of keyframe-indexed video streams,
executed by a master/worker runtime that decodes exactly the frames each task
needs and runs kernels as JAX/XLA programs on TPU.
"""

from .common import (BlobType, BoundaryCondition, CacheMode, DeviceType,
                     FrameType, GraphException, JobException, NullElement,
                     PerfParams, ScannerException, SliceList, StorageException)

__version__ = "0.1.0"

__all__ = [
    "BlobType", "BoundaryCondition", "CacheMode", "DeviceType", "FrameType",
    "GraphException", "JobException", "NullElement", "PerfParams",
    "ScannerException", "SliceList", "StorageException",
]
