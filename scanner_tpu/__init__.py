"""scanner_tpu: a TPU-native framework for efficient analysis of large video
datasets.

Capabilities mirror scanner-research/scanner (SIGGRAPH 2018): computation
graphs (Source -> Ops -> Sink) over tables of keyframe-indexed video streams,
executed by a master/worker runtime that decodes exactly the frames each task
needs and runs kernels as JAX/XLA programs on TPU.
"""

from .common import (BlobType, BoundaryCondition, CacheMode, DeviceType,
                     FrameType, GraphException, JobException, NullElement,
                     PerfParams, ScannerException, SliceList, StorageException)

from .engine.client import Client, Table
from .graph.ops import Kernel, KernelConfig, register_op
from .storage.streams import NamedStream, NamedVideoStream, StoredStream

# reference-compat alias
register_python_op = register_op

__version__ = "0.1.0"

__all__ = [
    "BlobType", "BoundaryCondition", "CacheMode", "DeviceType", "FrameType",
    "GraphException", "JobException", "NullElement", "PerfParams",
    "ScannerException", "SliceList", "StorageException",
    "Client", "Table", "Kernel", "KernelConfig", "register_op",
    "register_python_op", "NamedStream", "NamedVideoStream", "StoredStream",
]
