"""Offline analyses of the scanner_tpu codebase itself.

`analysis.static` is the repo-native static-analysis suite
(`tools/scanner_check.py` / the `scanner-check` console script): AST
passes that enforce the program properties the engine's correctness and
performance story depend on — tracer safety of jitted/device-kernel
code, lock-order discipline in the threaded pipeline, and the
code↔docs↔wiring contracts (metric catalog, env vars, config keys,
fault sites, RPC surface).  See docs/static-analysis.md.
"""

from . import static  # noqa: F401
