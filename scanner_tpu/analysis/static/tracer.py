"""Tracer-safety / recompile lints (SC101–SC105).

Jitted code is *traced*: python runs once with abstract values, and
anything that escapes the tracer — a numpy reduction, an `if` on a
data-dependent value, a wall-clock read — either crashes at trace time,
silently bakes a stale constant into the executable, or (worst) forces
a fresh XLA compile per call shape.  The engine's whole perf story
(bucketed dispatch, the 8→3 executable reduction, persistent cache
hits) assumes kernels are pure, shape-stable functions; these passes
make that assumption reviewable.

Codes
  SC101  numpy call on a traced value inside jitted code
  SC102  host control flow / concretization (`if`/`while`/`bool()`/
         `int()`) on a traced value
  SC103  nondeterminism inside jitted code (wall clock, `random`,
         `np.random`, uuid, os.urandom)
  SC104  mutable module global captured inside jitted code (trace-time
         snapshot goes stale; mutation never reaches the executable)
  SC105  raw-shape jitted call: a device-kernel `execute()` outside the
         engine's bucketed dispatch, or a jitted function called with a
         variable-length slice (every length mints an executable)
  SC106  default-chip device placement inside engine/kernels code:
         `jax.devices()[0]` / `jax.local_devices()[0]` pins, or a bare
         `device_put` without an explicit device — under evaluator
         affinity every placement must name its chip (or thread the
         instance's device through), else N-1 chips idle while chip 0
         takes every stdlib kernel
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisPass, Finding, ModuleInfo, Project

# attributes of a traced array that are static (python values) at trace
# time — touching them is how shape-dependent code SHOULD branch
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding",
                 "aval", "weak_type"}
# builtins whose result is static even on traced args
_STATIC_FUNCS = {"len", "isinstance", "issubclass", "type", "range",
                 "hasattr", "getattr", "enumerate"}
# traced-value methods returning static python values
_STATIC_METHODS = {"item"}  # .item() concretizes — errors loudly on its own

# dotted-suffix of a tracing wrapper -> indices of its function args
_FN_ARG_WRAPPERS = {
    "jit": (0,), "pmap": (0,), "vmap": (0,),
    "shard_map": (0,),
    "lax.scan": (0,),
    "lax.while_loop": (0, 1),
    "lax.fori_loop": (2,),
    "lax.cond": (1, 2),
    "lax.switch": (1,),
    "pallas_call": (0,),
    "checkpoint": (0,), "remat": (0,),
}


def _wrapper_fn_indices(name: Optional[str]) -> Optional[Tuple[int, ...]]:
    """Function-arg indices if `name` (a dotted call target) is a
    tracing wrapper; matched on trailing dotted components so jax.jit /
    jax.lax.scan / pl.pallas_call all resolve however they're aliased."""
    if not name:
        return None
    parts = name.split(".")
    for pat, idxs in _FN_ARG_WRAPPERS.items():
        pp = pat.split(".")
        if parts[-len(pp):] == pp:
            return idxs
    return None

_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.time_ns", "time.perf_counter_ns",
                "datetime.datetime.now", "datetime.datetime.utcnow",
                "datetime.now", "datetime.utcnow",
                "uuid.uuid4", "uuid.uuid1", "os.urandom"}
_MUTATOR_METHODS = {"append", "extend", "add", "update", "pop", "popitem",
                    "remove", "discard", "clear", "insert", "setdefault"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _import_aliases(mod: ModuleInfo) -> Dict[str, str]:
    """local name -> dotted module/object it refers to."""
    out: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _resolve(mod_aliases: Dict[str, str], dotted: Optional[str]
             ) -> Optional[str]:
    """Rewrite the leading alias of a dotted name to its import target:
    np.random.rand -> numpy.random.rand."""
    if not dotted:
        return None
    head, _, rest = dotted.partition(".")
    target = mod_aliases.get(head)
    if target is None:
        return dotted
    return f"{target}.{rest}" if rest else target


def _static_argnames(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return names


def _static_argnums(call: ast.Call) -> Set[int]:
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnum"):
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return nums


class _JitContext:
    def __init__(self, fn: ast.FunctionDef, static_names: Set[str],
                 reason: str):
        self.fn = fn
        self.static_names = static_names
        self.reason = reason  # what marked it jitted, for messages


def _find_jit_contexts(mod: ModuleInfo, aliases: Dict[str, str]
                       ) -> List[_JitContext]:
    """Functions whose bodies run under a JAX trace: jit/pmap/vmap
    decorated, functools.partial(jax.jit, ...) decorated, or passed by
    name/position into jit wrappers (shard_map, lax control flow,
    pallas_call)."""
    defs_by_name: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, node)

    ctxs: Dict[ast.FunctionDef, _JitContext] = {}

    def mark(fn: ast.FunctionDef, static: Set[str], reason: str) -> None:
        if fn not in ctxs:
            ctxs[fn] = _JitContext(fn, static, reason)
        else:
            ctxs[fn].static_names |= static

    def nums_to_names(fn: ast.FunctionDef, nums: Set[int]) -> Set[str]:
        args = [a.arg for a in fn.args.args]
        return {args[i] for i in nums if 0 <= i < len(args)}

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                d = _resolve(aliases, dotted_name(dec))
                if d and d.split(".")[-1] in ("jit", "pmap", "vmap"):
                    mark(node, set(), d)
                elif isinstance(dec, ast.Call):
                    inner = _resolve(aliases, dotted_name(dec.func))
                    if inner and inner.split(".")[-1] == "partial" \
                            and dec.args:
                        wrapped = _resolve(aliases,
                                           dotted_name(dec.args[0]))
                        if wrapped and wrapped.split(".")[-1] in (
                                "jit", "pmap"):
                            static = _static_argnames(dec) | nums_to_names(
                                node, _static_argnums(dec))
                            mark(node, static, wrapped)
                    elif inner and inner.split(".")[-1] in ("jit", "pmap",
                                                            "vmap"):
                        static = _static_argnames(dec) | nums_to_names(
                            node, _static_argnums(dec))
                        mark(node, static, inner)
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            idxs = _wrapper_fn_indices(d) \
                or _wrapper_fn_indices(_resolve(aliases, d))
            if not idxs:
                continue
            for i in idxs:
                if i < len(node.args) and isinstance(node.args[i],
                                                     ast.Name):
                    fn = defs_by_name.get(node.args[i].id)
                    if fn is not None:
                        static = _static_argnames(node) \
                            | nums_to_names(fn, _static_argnums(node))
                        mark(fn, static, d or "wrapper")
    return list(ctxs.values())


class _TracedExpr:
    """Conservative 'does this expression carry a traced value'
    evaluator over a set of known-traced local names."""

    def __init__(self, traced: Set[str]):
        self.traced = traced

    def check(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.check(node.value)
        if isinstance(node, ast.Subscript):
            # x[i] is traced if x is; shape[0] is static because .shape
            # already returned False above
            return self.check(node.value)
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in _STATIC_FUNCS:
                return False
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _STATIC_METHODS:
                    return False
                if self.check(node.func.value):
                    return True
            return any(self.check(a) for a in node.args) or any(
                self.check(kw.value) for kw in node.keywords)
        if isinstance(node, (ast.BinOp,)):
            return self.check(node.left) or self.check(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.check(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.check(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.check(node.left) or any(
                self.check(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.check(node.body) or self.check(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.check(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.check(node.value)
        return False


def _mutable_globals(mod: ModuleInfo) -> Set[str]:
    """Module-level names bound to mutable containers AND mutated from
    inside some function body (import-time population — the decorator
    registry pattern — is fine: it happens before any trace)."""
    mutable: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            v = stmt.value
            is_mut = isinstance(v, (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp))
            if isinstance(v, ast.Call):
                ctor = dotted_name(v.func) or ""
                is_mut = ctor.split(".")[-1] in (
                    "list", "dict", "set", "defaultdict", "deque",
                    "Counter", "OrderedDict", "bytearray")
            if is_mut:
                mutable.add(stmt.targets[0].id)
    if not mutable:
        return set()
    mutated: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, ast.Name) and sub.target.id in mutable:
                mutated.add(sub.target.id)
            elif isinstance(sub, (ast.Assign,)):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name) and t.value.id in mutable:
                        mutated.add(t.value.id)
            elif isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATOR_METHODS \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id in mutable:
                mutated.add(sub.func.value.id)
    return mutable & mutated


class TracerSafetyPass(AnalysisPass):
    name = "tracer"
    codes = {
        "SC101": "numpy call on a traced value inside jitted code",
        "SC102": "host control flow / concretization on a traced value",
        "SC103": "nondeterminism (clock/random) inside jitted code",
        "SC104": "mutable module global captured inside jitted code",
        "SC105": "raw-shape jitted call bypassing bucketed dispatch",
        "SC106": "default-chip device placement in engine/kernels code",
    }

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            aliases = _import_aliases(mod)
            mut_globals = _mutable_globals(mod)
            jitted_names: Set[str] = set()
            for ctx in _find_jit_contexts(mod, aliases):
                jitted_names.add(ctx.fn.name)
                out.extend(self._check_context(mod, aliases, ctx,
                                               mut_globals))
            # names rebound from jit wrappers: f = jax.jit(g)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call):
                    d = _resolve(aliases, dotted_name(node.value.func))
                    if d and d.split(".")[-1] in ("jit", "pmap"):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                jitted_names.add(t.id)
            out.extend(self._check_raw_shape_calls(mod, jitted_names))
            out.extend(self._check_device_affinity(mod, aliases))
        return out

    # -- SC101..SC104 over one jit context ------------------------------

    def _check_context(self, mod: ModuleInfo, aliases: Dict[str, str],
                       ctx: _JitContext, mut_globals: Set[str]
                       ) -> List[Finding]:
        fn = ctx.fn
        out: List[Finding] = []
        params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                                  + fn.args.kwonlyargs)}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        traced = params - ctx.static_names
        te = _TracedExpr(traced)

        # nested defs trace too; their params are (slices of) tracers
        body_nodes = list(ast.walk(fn))
        for sub in body_nodes:
            if isinstance(sub, ast.FunctionDef) and sub is not fn:
                traced.update(a.arg for a in sub.args.args)

        # two propagation sweeps: handles simple forward def-use chains
        # plus one level of later-defined helper use
        for _ in range(2):
            for sub in body_nodes:
                if isinstance(sub, ast.Assign) and te.check(sub.value):
                    for t in sub.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                traced.add(n.id)
                elif isinstance(sub, ast.AugAssign) and isinstance(
                        sub.target, ast.Name):
                    if te.check(sub.value) or sub.target.id in traced:
                        traced.add(sub.target.id)
                elif isinstance(sub, ast.For) and te.check(sub.iter):
                    for n in ast.walk(sub.target):
                        if isinstance(n, ast.Name):
                            traced.add(n.id)

        for sub in body_nodes:
            if isinstance(sub, (ast.If, ast.While)) and te.check(sub.test):
                kind = "while" if isinstance(sub, ast.While) else "if"
                out.append(mod.finding(
                    "SC102",
                    f"host `{kind}` on traced value inside jitted "
                    f"`{fn.name}` — use jnp.where/lax.cond (or branch on "
                    ".shape/.ndim, which are static)", sub))
            elif isinstance(sub, ast.Assert) and te.check(sub.test):
                out.append(mod.finding(
                    "SC102",
                    f"assert on traced value inside jitted `{fn.name}` "
                    "concretizes at trace time", sub))
            elif isinstance(sub, ast.Call):
                fname = dotted_name(sub.func)
                resolved = _resolve(aliases, fname) or ""
                root = (fname or "").split(".")[0]
                root_target = aliases.get(root, root)
                if fname in ("bool", "int", "float") and any(
                        te.check(a) for a in sub.args):
                    out.append(mod.finding(
                        "SC102",
                        f"`{fname}()` concretizes a traced value inside "
                        f"jitted `{fn.name}`", sub))
                elif root_target == "numpy" or resolved.startswith(
                        "numpy."):
                    if ".random" in f".{resolved}" or (
                            fname or "").startswith(f"{root}.random."):
                        out.append(mod.finding(
                            "SC103",
                            f"`{fname}` inside jitted `{fn.name}`: host "
                            "RNG is drawn once at trace time — use "
                            "jax.random with an explicit key", sub))
                    elif any(te.check(a) for a in sub.args) or any(
                            te.check(kw.value) for kw in sub.keywords):
                        out.append(mod.finding(
                            "SC101",
                            f"`{fname}` applied to a traced value inside "
                            f"jitted `{fn.name}` — numpy silently "
                            "concretizes (ConcretizationTypeError at "
                            "best, a baked-in constant at worst); use "
                            "jnp", sub))
                elif resolved in _CLOCK_CALLS or (
                        fname or "") in _CLOCK_CALLS:
                    out.append(mod.finding(
                        "SC103",
                        f"`{fname}` inside jitted `{fn.name}` is evaluated "
                        "once at trace time (stale constant in the "
                        "executable)", sub))
                elif root_target == "random" and "." in (fname or ""):
                    out.append(mod.finding(
                        "SC103",
                        f"`{fname}` inside jitted `{fn.name}`: host RNG "
                        "inside a trace — use jax.random", sub))
            elif isinstance(sub, ast.Global):
                out.append(mod.finding(
                    "SC104",
                    f"`global {', '.join(sub.names)}` inside jitted "
                    f"`{fn.name}`: writes never reach the compiled "
                    "executable", sub))
            elif isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, ast.Load) and sub.id in mut_globals \
                    and sub.id not in traced and sub.id not in params:
                out.append(mod.finding(
                    "SC104",
                    f"mutable module global `{sub.id}` read inside jitted "
                    f"`{fn.name}` — captured as a trace-time snapshot; "
                    "later mutations are silently ignored (pass it as an "
                    "argument instead)", sub))
        return out

    # -- SC105 ----------------------------------------------------------

    def _check_raw_shape_calls(self, mod: ModuleInfo,
                               jitted_names: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        in_engine_dispatch = mod.relpath.endswith("engine/evaluate.py")
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # kernel.execute() outside engine/evaluate.py: the ONLY
            # blessed device-kernel call path is bucketed dispatch
            if isinstance(f, ast.Attribute) and f.attr == "execute" \
                    and isinstance(f.value, ast.Attribute) \
                    and f.value.attr == "kernel" \
                    and not in_engine_dispatch:
                out.append(mod.finding(
                    "SC105",
                    "direct device-kernel execute() outside "
                    "engine/evaluate.py bypasses the bucket ladder — "
                    "every novel call shape mints an XLA executable",
                    node))
                continue
            # jitted_fn(x[:k]) with a non-constant slice bound: the call
            # shape varies with k, defeating shape-stable dispatch
            callee = f.id if isinstance(f, ast.Name) else None
            if callee in jitted_names:
                for a in node.args:
                    if isinstance(a, ast.Subscript) and isinstance(
                            a.slice, ast.Slice):
                        bounds = (a.slice.lower, a.slice.upper)
                        if any(b is not None and not isinstance(
                                b, ast.Constant) for b in bounds):
                            out.append(mod.finding(
                                "SC105",
                                f"jitted `{callee}` called with a "
                                "variable-length slice — each length is "
                                "a fresh (shape, dtype) signature / XLA "
                                "compile; round up via "
                                "engine.evaluate.bucket_for", node))
                            break
        return out

    # -- SC106 ----------------------------------------------------------

    def _check_device_affinity(self, mod: ModuleInfo,
                               aliases: Dict[str, str]) -> List[Finding]:
        """Engine/kernels code must never hard-pin the default chip:
        evaluator affinity (engine/evaluate.py assigned_device) hands
        every call site an explicit device, and `jax.devices()[0]` or a
        bare `device_put(x)` silently routes work back to chip 0 —
        exactly the N-1-chips-idle failure the affinity work removed.
        Passing a possibly-None device variable is fine (placement was
        decided upstream); omitting the argument is not."""
        parts = mod.relpath.replace("\\", "/").split("/")
        if "engine" not in parts and "kernels" not in parts:
            return []
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Subscript) and isinstance(
                    node.value, ast.Call):
                resolved = _resolve(
                    aliases, dotted_name(node.value.func)) or ""
                if resolved in ("jax.devices", "jax.local_devices") \
                        and isinstance(node.slice, ast.Constant):
                    out.append(mod.finding(
                        "SC106",
                        f"`{resolved}()[...]` pins a fixed chip inside "
                        "engine/kernels code — use the evaluator's "
                        "assigned device (engine.evaluate"
                        ".assigned_device) or jax.default_backend() "
                        "for platform probes", node))
            elif isinstance(node, ast.Call):
                resolved = _resolve(aliases, dotted_name(node.func)) or ""
                if resolved == "jax.device_put" \
                        and len(node.args) < 2 \
                        and not any(kw.arg == "device"
                                    for kw in node.keywords):
                    out.append(mod.finding(
                        "SC106",
                        "bare `jax.device_put(x)` dispatches to the "
                        "default chip — pass the target device "
                        "explicitly (ColumnBatch.to_device(device=...) "
                        "/ the instance's assigned_device)", node))
        return out
