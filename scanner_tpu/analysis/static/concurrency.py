"""Concurrency lints over the threaded pipeline (SC201–SC203).

The engine runs ~a dozen thread kinds (stage loaders/evaluators/savers,
heartbeat, master scan loop, metrics scrapes, warm-up) against ~19
Lock/RLock sites.  Deadlocks and torn state don't reproduce in unit
tests; the only cheap time to catch them is statically, at review:

  SC201  lock-order hazard: two locks acquired in opposite orders on
         different paths (ABBA deadlock), or a non-reentrant Lock
         re-acquired on a path that may already hold it
  SC202  blocking call while holding a lock: RPC, storage/file I/O,
         sleeps, unbounded queue/event waits — one slow peer and every
         thread contending that lock convoys behind it
  SC203  attribute written both under a lock and bare: the bare write
         races the locked readers/writers (lost update, torn check)

The analysis is intentionally first-order: locks are identified by
their declaration site (`self._x = threading.Lock()` in class C →
"mod.C._x"; module-level `L = threading.Lock()` → "mod.L"), acquisition
by `with <lock>:`, and call edges one level deep (self-methods within a
class, bare functions within a module).  That shallow model already
covers every lock in this codebase; anything it can't see, it stays
silent about (no speculative aliasing)."""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisPass, Finding, ModuleInfo, Project
from .tracer import dotted_name

# receiver-method calls considered blocking when made under a lock.
# (name-based: precise enough at this codebase's idiom, and a false
# positive is one inline suppression away)
_BLOCKING_SIMPLE = {"time.sleep", "wait_for_server", "subprocess.run",
                    "subprocess.check_call", "subprocess.check_output",
                    "subprocess.Popen"}
_RPC_METHODS = {"call", "try_call"}
_STORAGE_METHODS = {"read", "read_range", "write", "write_exclusive",
                    "list_prefix", "delete", "delete_prefix"}
_STORAGE_RECEIVER_HINTS = ("storage", "backend")
_QUEUE_RECEIVER_HINTS = ("q", "queue")


def _mod_base(mod: ModuleInfo) -> str:
    return mod.relpath[:-3].replace("/", ".")


@dataclass
class _LockDecl:
    key: str        # "engine.service.Master._lock"
    reentrant: bool


@dataclass
class _FuncInfo:
    mod: ModuleInfo
    cls: Optional[str]
    fn: ast.FunctionDef
    acquires: Set[str] = field(default_factory=set)  # direct only


class _ClassModel:
    def __init__(self, mod: ModuleInfo, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.locks: Dict[str, _LockDecl] = {}   # attr -> decl
        self.methods: Dict[str, ast.FunctionDef] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                t = sub.targets[0] if len(sub.targets) == 1 else None
                if isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name) and t.value.id == "self":
                    kind = _lock_ctor(sub.value)
                    if kind:
                        self.locks[t.attr] = _LockDecl(
                            f"{_mod_base(mod)}.{self.name}.{t.attr}",
                            reentrant=(kind == "RLock"))
        for sub in node.body:
            if isinstance(sub, ast.FunctionDef):
                self.methods[sub.name] = sub


def _lock_ctor(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        d = dotted_name(value.func) or ""
        last = d.split(".")[-1]
        if last in ("Lock", "RLock"):
            return last
    return None


def _module_locks(mod: ModuleInfo) -> Dict[str, _LockDecl]:
    out: Dict[str, _LockDecl] = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            kind = _lock_ctor(stmt.value)
            if kind:
                name = stmt.targets[0].id
                out[name] = _LockDecl(f"{_mod_base(mod)}.{name}",
                                      reentrant=(kind == "RLock"))
    return out


@dataclass
class _Edge:
    src: str
    dst: str
    mod: ModuleInfo
    node: ast.AST       # where dst is acquired (or the call site)
    via: str = ""       # call chain note for the message


class ConcurrencyPass(AnalysisPass):
    name = "concurrency"
    codes = {
        "SC201": "inconsistent lock acquisition order / self-deadlock",
        "SC202": "blocking call while holding a lock",
        "SC203": "shared attribute written outside its lock",
    }

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        edges: List[_Edge] = []
        lock_decls: Dict[str, _LockDecl] = {}

        for mod in project.modules:
            mod_locks = _module_locks(mod)
            lock_decls.update({d.key: d for d in mod_locks.values()})
            classes = [
                _ClassModel(mod, n) for n in mod.tree.body
                if isinstance(n, ast.ClassDef)]
            module_funcs = {n.name: n for n in mod.tree.body
                            if isinstance(n, ast.FunctionDef)}
            for cm in classes:
                lock_decls.update({d.key: d for d in cm.locks.values()})

            for cm in classes:
                for mname, fn in cm.methods.items():
                    self._walk_function(
                        mod, fn, cm, mod_locks, classes, module_funcs,
                        edges, findings)
                findings.extend(self._check_unguarded_writes(mod, cm))
            for fname, fn in module_funcs.items():
                self._walk_function(mod, fn, None, mod_locks, classes,
                                    module_funcs, edges, findings)

        findings.extend(self._order_findings(edges, lock_decls))
        return findings

    # -- lock model helpers ---------------------------------------------

    @staticmethod
    def _lock_of_expr(expr: ast.AST, cls: Optional[_ClassModel],
                      mod_locks: Dict[str, _LockDecl]
                      ) -> Optional[_LockDecl]:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" \
                and cls is not None:
            return cls.locks.get(expr.attr)
        if isinstance(expr, ast.Name):
            return mod_locks.get(expr.id)
        return None

    @staticmethod
    def _direct_acquires(fn: ast.FunctionDef, cls: Optional[_ClassModel],
                         mod_locks: Dict[str, _LockDecl]) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    d = ConcurrencyPass._lock_of_expr(
                        item.context_expr, cls, mod_locks)
                    if d:
                        out.add(d.key)
        return out

    # -- per-function walk: edges + SC202 -------------------------------

    def _walk_function(self, mod: ModuleInfo, fn: ast.FunctionDef,
                       cls: Optional[_ClassModel],
                       mod_locks: Dict[str, _LockDecl],
                       classes: Sequence[_ClassModel],
                       module_funcs: Dict[str, ast.FunctionDef],
                       edges: List[_Edge],
                       findings: List[Finding]) -> None:
        class_by_name = {c.name: c for c in classes}

        def callee_acquires(call: ast.Call) -> Tuple[Set[str], str]:
            """Locks a one-level-resolved callee acquires directly."""
            f = call.func
            if isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name) and f.value.id == "self" \
                    and cls is not None:
                target = cls.methods.get(f.attr)
                if target is not None:
                    return (self._direct_acquires(target, cls, mod_locks),
                            f"self.{f.attr}()")
            elif isinstance(f, ast.Name):
                target = module_funcs.get(f.id)
                if target is not None:
                    return (self._direct_acquires(target, None, mod_locks),
                            f"{f.id}()")
                c = class_by_name.get(f.id)
                if c is not None and "__init__" in c.methods:
                    return (self._direct_acquires(
                        c.methods["__init__"], c, mod_locks),
                        f"{f.id}()")
            return set(), ""

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, ast.With):
                new_held = held
                for i in node.items:
                    d = self._lock_of_expr(i.context_expr, cls, mod_locks)
                    if d is None:
                        # a non-lock context manager may still make calls
                        visit(i.context_expr, new_held)
                        continue
                    for h in new_held:
                        edges.append(_Edge(h, d.key, mod, node))
                    new_held = new_held + (d.key,)
                for child in node.body:
                    visit(child, new_held)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                # nested def: runs later, not under the current locks
                return
            if isinstance(node, ast.Call) and held:
                self._check_blocking(mod, node, held, findings)
                acq, via = callee_acquires(node)
                for key in acq:
                    for h in held:
                        edges.append(_Edge(h, key, mod, node, via=via))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(fn, ())

    def _check_blocking(self, mod: ModuleInfo, call: ast.Call,
                        held: Tuple[str, ...],
                        findings: List[Finding]) -> None:
        d = dotted_name(call.func) or ""
        lockset = ", ".join(k.rsplit(".", 2)[-2] + "." +
                            k.rsplit(".", 2)[-1] for k in held)
        kwnames = {kw.arg for kw in call.keywords}

        def hit(what: str) -> None:
            findings.append(mod.finding(
                "SC202",
                f"{what} while holding {lockset} — every thread "
                "contending that lock convoys behind this call", call))

        if d in _BLOCKING_SIMPLE or d.endswith(".sleep"):
            hit(f"`{d}` (blocking)")
            return
        if isinstance(call.func, ast.Attribute):
            meth = call.func.attr
            recv = dotted_name(call.func.value) or ""
            recv_last = recv.split(".")[-1].lower()
            if meth in _RPC_METHODS:
                hit(f"RPC `{recv}.{meth}()`")
            elif meth in _STORAGE_METHODS and any(
                    h in recv_last for h in _STORAGE_RECEIVER_HINTS):
                hit(f"storage I/O `{recv}.{meth}()`")
            elif meth == "get" and "timeout" not in kwnames \
                    and not call.args \
                    and any(recv_last == h or recv_last.endswith("_" + h)
                            or recv_last.endswith(h)
                            for h in _QUEUE_RECEIVER_HINTS) \
                    and recv_last not in ("config",):
                hit(f"unbounded `{recv}.get()`")
            elif meth in ("wait", "join") and not call.args \
                    and "timeout" not in kwnames and recv:
                hit(f"unbounded `{recv}.{meth}()`")
        elif isinstance(call.func, ast.Name) and call.func.id == "open":
            hit("file I/O `open()`")

    # -- SC201 ----------------------------------------------------------

    def _order_findings(self, edges: List[_Edge],
                        decls: Dict[str, _LockDecl]) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        graph: Dict[str, Set[str]] = {}
        for e in edges:
            graph.setdefault(e.src, set()).add(e.dst)

        # self-acquisition of a non-reentrant Lock
        for e in edges:
            if e.src == e.dst and not decls.get(
                    e.dst, _LockDecl(e.dst, False)).reentrant:
                if ("self", e.dst) in seen:
                    continue
                seen.add(("self", e.dst))
                via = f" via {e.via}" if e.via else ""
                out.append(e.mod.finding(
                    "SC201",
                    f"non-reentrant Lock `{_short(e.dst)}` re-acquired on "
                    f"a path that may already hold it{via} — instant "
                    "self-deadlock", e.node))

        # opposite-order pairs (ABBA)
        for e in edges:
            if e.src == e.dst:
                continue
            if e.src in graph.get(e.dst, ()):  # dst -> src exists too
                pair = tuple(sorted((e.src, e.dst)))
                if ("abba",) + pair in seen:
                    continue
                seen.add(("abba",) + pair)
                out.append(e.mod.finding(
                    "SC201",
                    f"lock order inversion: `{_short(e.src)}` -> "
                    f"`{_short(e.dst)}` here, but the opposite order "
                    "exists elsewhere — ABBA deadlock when the two "
                    "paths interleave", e.node))
        return out

    # -- SC203 ----------------------------------------------------------

    def _check_unguarded_writes(self, mod: ModuleInfo,
                                cm: _ClassModel) -> List[Finding]:
        if not cm.locks:
            return []
        locked_attrs: Set[str] = set()
        unlocked_sites: Dict[str, List[ast.AST]] = {}

        for mname, fn in cm.methods.items():
            if mname == "__init__":
                continue  # construction happens-before publication

            def visit(node: ast.AST, held: bool) -> None:
                if isinstance(node, ast.With):
                    now_held = held or any(
                        self._lock_of_expr(i.context_expr, cm, {})
                        for i in node.items)
                    for child in node.body:
                        visit(child, now_held)
                    return
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AugAssign):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self" \
                            and t.attr not in cm.locks:
                        if held:
                            locked_attrs.add(t.attr)
                        else:
                            unlocked_sites.setdefault(
                                t.attr, []).append(node)
                for child in ast.iter_child_nodes(node):
                    visit(child, held)

            visit(fn, False)

        out: List[Finding] = []
        for attr in sorted(locked_attrs):
            for site in unlocked_sites.get(attr, []):
                out.append(mod.finding(
                    "SC203",
                    f"`self.{attr}` is written under "
                    f"`{cm.name}`'s lock elsewhere but bare here — the "
                    "unlocked write races the locked readers", site))
        return out


def _short(key: str) -> str:
    return ".".join(key.rsplit(".", 2)[-2:])
