"""Pass framework for the repo-native static analyzer (scanner-check).

The engine promises its users that scheduling, shape stability, and
fault tolerance are the engine's problem — which means the properties
those promises rest on must hold *of the engine's own source*.  This
module is the skeleton that lets each property be written as a small
AST pass:

  * `ModuleInfo` — one parsed source file: AST with parent/scope maps,
    raw lines, inline-suppression lookup;
  * `Project` — the set of modules under analysis plus repo context the
    contract passes need (docs text, repo root);
  * `AnalysisPass` — base class; a pass walks the project and returns
    `Finding`s, each tagged with a stable code (SCxxx);
  * suppression — inline (`# scanner-check: disable=SC202 reason`) for
    single sites, or a committed JSON baseline whose entries carry
    line-number-independent fingerprints plus a mandatory one-line
    justification (reviewed like code).

Passes live in tracer.py / concurrency.py / contracts.py; the CLI in
cli.py (tools/scanner_check.py and the `scanner-check` console script
both call it).  docs/static-analysis.md is the user-facing page.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "ModuleInfo", "Project", "AnalysisPass",
    "CallGraph", "PathSimulator",
    "load_baseline", "write_baseline", "BaselineError",
    "split_findings", "find_repo_root",
]

# inline suppression: a trailing comment on the offending line —
#   x = np.sum(y)  # scanner-check: disable=SC101 host reduction is intended
_SUPPRESS_RE = re.compile(
    r"#\s*scanner-check:\s*disable=([A-Z0-9,\s]+?)(?:\s+\S.*)?$")
# whole-file opt-out (generated files, vendored code) in the first lines
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*scanner-check:\s*disable-file=([A-Z0-9,\s]+?)(?:\s+\S.*)?$")
_FILE_PRAGMA_WINDOW = 10


@dataclass(frozen=True)
class Finding:
    """One analyzer hit.  `fingerprint` is stable under unrelated edits:
    it hashes the *snippet text* (whitespace-collapsed), not the line
    number, so a baseline survives code moving around it."""

    code: str          # e.g. "SC202"
    message: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    scope: str         # enclosing Class.method / function qualname, or ""
    snippet: str = ""  # source line the finding anchors to

    @property
    def fingerprint(self) -> str:
        norm = " ".join(self.snippet.split())
        h = hashlib.sha1(
            f"{self.code}|{self.path}|{self.scope}|{norm}".encode()
        ).hexdigest()[:12]
        return f"{self.code}:{self.path}:{self.scope or '<module>'}:{h}"

    def format(self) -> str:
        where = f" [{self.scope}]" if self.scope else ""
        return f"{self.path}:{self.line}: {self.code} {self.message}{where}"

    def to_dict(self) -> dict:
        return {"code": self.code, "message": self.message,
                "path": self.path, "line": self.line, "scope": self.scope,
                "snippet": self.snippet, "fingerprint": self.fingerprint}


class ModuleInfo:
    """One parsed python file plus the lookups every pass needs."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._scopes: Dict[ast.AST, str] = {}
        self._index(self.tree, None, ())
        self._file_suppressed = self._file_pragmas()

    @classmethod
    def parse(cls, path: str, root: str) -> "ModuleInfo":
        with open(path, encoding="utf-8") as f:
            src = f.read()
        return cls(path, os.path.relpath(path, root), src)

    def _index(self, node: ast.AST, parent: Optional[ast.AST],
               scope: Tuple[str, ...]) -> None:
        if parent is not None:
            self._parents[node] = parent
        self._scopes[node] = ".".join(scope)
        child_scope = scope
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            child_scope = scope + (node.name,)
        for child in ast.iter_child_nodes(node):
            self._index(child, node, child_scope)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def scope_of(self, node: ast.AST) -> str:
        """Qualname of the class/function enclosing `node` (the node's
        own name included when it is itself a def/class)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            base = self._scopes.get(node, "")
            return f"{base}.{node.name}" if base else node.name
        return self._scopes.get(node, "")

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def _file_pragmas(self) -> Set[str]:
        codes: Set[str] = set()
        for text in self.lines[:_FILE_PRAGMA_WINDOW]:
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                codes.update(c.strip() for c in m.group(1).split(",")
                             if c.strip())
        return codes

    def suppressed(self, code: str, lineno: int) -> bool:
        """Inline suppression on the finding's own line (or the file
        pragma).  `ALL` disables every code."""
        if self._file_suppressed & {code, "ALL"}:
            return True
        m = _SUPPRESS_RE.search(self.line_text(lineno))
        if not m:
            return False
        codes = {c.strip() for c in m.group(1).split(",")}
        return bool(codes & {code, "ALL"})

    def finding(self, code: str, message: str, node: ast.AST) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(code=code, message=message, path=self.relpath,
                       line=line, scope=self.scope_of(node),
                       snippet=self.line_text(line).strip())


def find_repo_root(start: str) -> str:
    """Walk up from `start` to the checkout root (setup.py/pytest.ini)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        if any(os.path.exists(os.path.join(d, probe))
               for probe in ("setup.py", "pytest.ini", ".git")):
            return d
        up = os.path.dirname(d)
        if up == d:
            return os.path.abspath(start)
        d = up


def _collect_py(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                out.extend(os.path.join(dirpath, f)
                           for f in filenames if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(set(os.path.abspath(p) for p in out))


class Project:
    """Everything a pass may look at: the parsed modules plus repo-level
    context (docs, auxiliary source trees) for the contract passes."""

    def __init__(self, paths: Sequence[str], root: Optional[str] = None):
        files = _collect_py(paths)
        if not files and not root:
            raise ValueError(f"no python files under {list(paths)}")
        self.root = os.path.abspath(
            root if root is not None
            else find_repo_root(files[0] if files else "."))
        self.modules: List[ModuleInfo] = []
        self.parse_errors: List[Finding] = []
        for f in files:
            try:
                self.modules.append(ModuleInfo.parse(f, self.root))
            except SyntaxError as e:
                rel = os.path.relpath(f, self.root).replace(os.sep, "/")
                self.parse_errors.append(Finding(
                    code="SC001", message=f"file does not parse: {e.msg}",
                    path=rel, line=e.lineno or 1, scope=""))
        self._docs_text: Optional[str] = None
        self._aux_sources: Optional[str] = None

    def module(self, rel_suffix: str) -> Optional[ModuleInfo]:
        """Find a module by repo-relative path suffix
        (e.g. 'util/faults.py')."""
        for m in self.modules:
            if m.relpath.endswith(rel_suffix):
                return m
        return None

    def docs_text(self) -> str:
        """Concatenated markdown under <root>/docs — the documentation
        side of every code↔docs contract."""
        if self._docs_text is None:
            parts = []
            docs = os.path.join(self.root, "docs")
            if os.path.isdir(docs):
                for name in sorted(os.listdir(docs)):
                    if name.endswith(".md"):
                        with open(os.path.join(docs, name),
                                  encoding="utf-8") as f:
                            parts.append(f.read())
            self._docs_text = "\n".join(parts)
        return self._docs_text

    def aux_source_text(self) -> str:
        """Raw text of tests/ and tools/ (not AST-analyzed — they are
        consumers, not the analyzed surface) so contract passes can tell
        'registered but unused anywhere' from 'used only by tests'."""
        if self._aux_sources is None:
            parts = []
            for sub in ("tests", "tools", "examples"):
                d = os.path.join(self.root, sub)
                if not os.path.isdir(d):
                    continue
                for dirpath, dirnames, filenames in os.walk(d):
                    dirnames[:] = [x for x in dirnames
                                   if x != "__pycache__"]
                    for fn in filenames:
                        if fn.endswith(".py"):
                            with open(os.path.join(dirpath, fn),
                                      encoding="utf-8") as f:
                                parts.append(f.read())
            self._aux_sources = "\n".join(parts)
        return self._aux_sources


class CallGraph:
    """Self-method call graph of one class — the interprocedural layer
    under the SC4xx durability passes.  The earlier passes resolve
    `self._helper()` exactly one level; this closes the relation so a
    pass can ask "which methods can *transitively* reach X" (a journal
    flush, a fence poll, a durable-state mutation) without re-walking
    the class per query.

    Edges include bare `self.method` references (not just calls): a
    method handed to `threading.Thread(target=self._loop)` is reachable
    from the spawning method for every safety question these passes
    ask."""

    def __init__(self, mod: "ModuleInfo", cls: ast.ClassDef):
        self.mod = mod
        self.cls = cls
        self.methods: Dict[str, ast.FunctionDef] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods.setdefault(stmt.name, stmt)
        self.callees: Dict[str, Set[str]] = {}
        names = set(self.methods)
        for name, fn in self.methods.items():
            refs: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in names:
                    refs.add(node.attr)
            self.callees[name] = refs
        self._closure: Dict[str, Set[str]] = {}

    def transitive_callees(self, name: str) -> Set[str]:
        """Every method reachable from `name` via self-references
        (`name` itself excluded unless it is recursive)."""
        cached = self._closure.get(name)
        if cached is not None:
            return cached
        seen: Set[str] = set()
        stack = list(self.callees.get(name, ()))
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(self.callees.get(m, ()))
        self._closure[name] = seen
        return seen

    def reaches(self, name: str, targets: Iterable[str]) -> bool:
        """Can `name` reach any of `targets` (directly or
        transitively)?"""
        t = set(targets)
        return bool(t & (self.transitive_callees(name) | {name}))

    def reaching(self, targets: Iterable[str]) -> Set[str]:
        """Reverse closure: every method that can reach any of
        `targets` (the targets themselves included when defined
        here)."""
        t = set(targets)
        return {m for m in self.methods
                if m in t or t & self.transitive_callees(m)}


class PathSimulator:
    """Per-path abstract interpretation over one function body — the
    path-sensitivity layer under SC401 (write-ahead discipline).

    Subclasses choose a state lattice (any immutable value) and
    override `initial`/`join`/`transfer`; the walker handles control
    flow so passes don't re-implement it:

      * `if` — union of both arms;
      * loops — fixpoint (body zero or more times);
      * `try` — handlers entered from *any prefix* of the body
        (an exception can strike between any two statements);
      * `finally` — applied before any `return` inside the `try`
        escapes (a handler that journals in `finally` commits before
        its ack leaves the function);
      * `return`/`raise` — terminate the path (`on_return` fires for
        returns, after enclosing `finally` bodies are applied).

    `on_end` fires with the state at the implicit fall-off-the-end
    return."""

    _FIXPOINT_LIMIT = 16

    def initial(self):
        return frozenset()

    def join(self, a, b):
        return a | b

    def transfer(self, state, stmt: ast.stmt):
        return state

    def on_return(self, state, node: ast.AST) -> None:
        pass

    def on_end(self, state, node: ast.AST) -> None:
        pass

    def run(self, fn: ast.FunctionDef) -> None:
        self._finally_stack: List[List[ast.stmt]] = []
        end = self._block(fn.body, self.initial())
        if end is not None:
            self.on_end(end, fn)

    # -- walker ----------------------------------------------------------

    def _block(self, stmts: Sequence[ast.stmt], state):
        """Returns the fall-through state, or None when every path
        terminated (return/raise/continue/break)."""
        for stmt in stmts:
            if state is None:
                break
            state = self._stmt(stmt, state)
        return state

    def _join_opt(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return self.join(a, b)

    def _stmt(self, stmt: ast.stmt, state):
        if isinstance(stmt, ast.Return):
            st = self.transfer(state, stmt)
            for finalbody in reversed(self._finally_stack):
                out = self._block(finalbody, st)
                if out is not None:
                    st = out
            self.on_return(st, stmt)
            return None
        if isinstance(stmt, ast.Raise):
            self.transfer(state, stmt)
            return None
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return None
        if isinstance(stmt, ast.If):
            return self._join_opt(self._block(stmt.body, state),
                                  self._block(stmt.orelse, state))
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            cur = state
            for _ in range(self._FIXPOINT_LIMIT):
                out = self._block(stmt.body, cur)
                nxt = cur if out is None else self.join(cur, out)
                if nxt == cur:
                    break
                cur = nxt
            return self._join_opt(cur, self._block(stmt.orelse, cur)
                                  if stmt.orelse else cur)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._block(stmt.body, self.transfer(state, stmt))
        if isinstance(stmt, ast.Try):
            handler_entry = state
            cur = state
            for s in stmt.body:
                if cur is None:
                    break
                if stmt.finalbody:
                    self._finally_stack.append(stmt.finalbody)
                try:
                    cur = self._stmt(s, cur)
                finally:
                    if stmt.finalbody:
                        self._finally_stack.pop()
                if cur is not None:
                    handler_entry = self.join(handler_entry, cur)
            out = None
            if cur is not None:
                out = self._block(stmt.orelse, cur) \
                    if stmt.orelse else cur
            for h in stmt.handlers:
                out = self._join_opt(out,
                                     self._block(h.body, handler_entry))
            if stmt.finalbody:
                if out is None:
                    # every path inside returned/raised — the finally
                    # still runs (returns already flowed through it via
                    # the stack), but nothing falls through
                    self._block(stmt.finalbody, handler_entry)
                    return None
                out = self._block(stmt.finalbody, out)
            return out
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state
        return self.transfer(state, stmt)


class AnalysisPass:
    """Base class: subclasses set `name`, document their `codes`, and
    implement run().  Finding codes are the stable public surface —
    suppressions and baselines refer to them, so codes are never
    renumbered."""

    name: str = ""
    codes: Dict[str, str] = {}

    def run(self, project: Project) -> List[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class BaselineError(Exception):
    pass


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry.  Every entry must carry a non-empty
    one-line justification: the baseline is a reviewed list of accepted
    exceptions, not a dumping ground."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    out: Dict[str, dict] = {}
    for e in entries:
        fp = e.get("fingerprint")
        if not fp:
            raise BaselineError(f"{path}: entry without fingerprint: {e}")
        just = (e.get("justification") or "").strip()
        if not just or just.upper().startswith("TODO"):
            raise BaselineError(
                f"{path}: entry {fp} lacks a justification — every "
                "baselined finding needs a one-line reason")
        if fp in out:
            raise BaselineError(
                f"{path}: duplicate fingerprint {fp} — one entry per "
                "accepted finding (merge the duplicates; a copy-paste "
                "here silently double-counts an exception)")
        out[fp] = e
    return out


def write_baseline(path: str, findings: Iterable[Finding],
                   previous: Optional[Dict[str, dict]] = None,
                   justification: str = "TODO: justify") -> int:
    """(Re)write the baseline from `findings`, keeping justifications of
    entries that persist from `previous`.  Returns the number of NEW
    entries (which carry the placeholder/bulk `justification` and must
    be edited before load_baseline will accept the file, unless a real
    justification was passed)."""
    previous = previous or {}
    entries, new = [], 0
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.code)):
        old = previous.get(f.fingerprint)
        if old is None:
            new += 1
        entries.append({
            "fingerprint": f.fingerprint,
            "code": f.code,
            "path": f.path,
            "scope": f.scope,
            "message": f.message,
            "justification": (old or {}).get("justification",
                                             justification),
        })
    doc = {"comment": "scanner-check accepted findings; every entry "
                      "needs a one-line justification "
                      "(docs/static-analysis.md)",
           "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return new


@dataclass
class SplitResult:
    unsuppressed: List[Finding] = field(default_factory=list)
    inline_suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)


def split_findings(project: Project, findings: Sequence[Finding],
                   baseline: Optional[Dict[str, dict]] = None
                   ) -> SplitResult:
    """Partition raw findings into actionable / inline-suppressed /
    baselined, and report baseline entries that no longer match
    anything (stale — they should be pruned)."""
    baseline = baseline or {}
    by_path = {m.relpath: m for m in project.modules}
    res = SplitResult()
    seen_fps: Set[str] = set()
    for f in findings:
        seen_fps.add(f.fingerprint)
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.code, f.line):
            res.inline_suppressed.append(f)
        elif f.fingerprint in baseline:
            res.baselined.append(f)
        else:
            res.unsuppressed.append(f)
    res.stale_baseline = sorted(set(baseline) - seen_fps)
    return res
