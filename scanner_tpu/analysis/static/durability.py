"""Durability & fencing data-flow passes (SC401–SC406).

The write-ahead journal, generation/epoch fencing, and shard-map CAS
(engine/{service,journal,shardmap}.py) carry the paper's fault-
tolerance promise, and their invariants are *semantic*: journal before
ack, fence before mutate, monotone staleness checks, replay arms for
every record type.  Chaos drills sample these dynamically; this pass
family checks them on every run of scanner-check, using the
interprocedural layer in core.py (CallGraph + PathSimulator):

  SC401  write-ahead discipline — a master RPC handler that creates a
         journal-intent record (`recs.append({"t": ...})`) or mutates
         replayed durable state must reach `_journal_append`/group-
         commit on every path before its ack (return).  Returns inside
         ``try`` bodies flow through ``finally`` first, so the
         journal-in-finally idiom is clean.
  SC402  path-sensitive fence coverage — durable-state mutations
         reachable from an *unfenced* entry point (handler registered
         without `self._fenced(...)`, or a background-thread target)
         with no fence consultation anywhere on the path.  SC312/313
         only audit registration wrapping; this follows the helpers.
  SC403  epoch/generation staleness discipline — a function that
         mutates durable/latch state and reads a stamped message field
         (`gen`/`generation`/`epoch`/`map_epoch`) must validate it
         with a CAS or a monotone (<, <=, >, >=) comparison — raw
         ==/!= equality, or no check at all, is flagged.  Passing the
         stamped dict (or the stamp) to a callee counts as delegation.
  SC404  journal-record round-trip — every record type appended
         (`{"t": <const>}`) must have a replay arm (a comparison
         against the record's ``t`` field) and appear in RECORD_TYPES,
         and vice versa, so recovery can never meet a record it does
         not understand (or keep a dead arm).
  SC405  no lock held across group-commit/collective waits — sharpens
         SC202: a call that (transitively) reaches `_journal_append`
         or a collective barrier while a `threading.Lock`-family
         attribute is held stalls every heartbeat behind storage.
  SC406  model anchoring — analysis/model/protocol.py (the bounded-
         interleaving protocol model run by tools/scanner_model.py)
         must anchor every transition to an RPC_CONTRACTS entry and
         cover every idempotent=False contract, both directions, so
         the model cannot rot away from the source.

Suppression/baseline semantics are the framework's
(docs/static-analysis.md); deliberate sites carry inline
justifications, genuine violations get fixed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (AnalysisPass, CallGraph, Finding, ModuleInfo,
                   PathSimulator, Project)
from .tracer import dotted_name
from .contracts import ContractPass, _const_str, _module_tuple

__all__ = ["DurabilityPass"]

# message fields that stamp a request/reply with an ordering token
_STAMP_KEYS = frozenset({"gen", "generation", "epoch", "map_epoch"})
# attributes journal replay (_apply_journal_records) restores — the
# durable-state surface the write-ahead contract covers
_DURABLE_ATTRS = frozenset({
    "done", "failures", "transient_failures", "blacklisted_jobs",
    "committed_jobs", "next_gang_id", "gang_epoch",
})
_SET_MUTATORS = frozenset({"add", "append", "update", "discard",
                           "remove", "pop", "clear"})
_CAS_NAMES = frozenset({"try_claim", "claim_generation",
                        "write_exclusive"})
# consulting any of these means the method participates in the fence
# protocol (SC402 credit): _journal_append itself checks
# self._fence.is_set() before any durable write
_FENCE_ATTRS = frozenset({"_fence", "_check_fence", "_fenced",
                          "_fence_out"})
_COLLECTIVE_WAITS = frozenset({"_collective_digest_sum",
                               "_all_gather_bytes", "all_gather",
                               "psum", "all_reduce", "barrier"})
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})

# effect-summary lattice for SC401 (what calling a method does to the
# caller's pending-journal state)
_EFFECT_NONE = "none"
_EFFECT_DIRTY = "dirty"    # leaves journal-intent/durable dirt pending
_EFFECT_FLUSH = "flush"    # group-commits (clears pending dirt)


def _last_name(node: Optional[ast.AST]) -> str:
    return (dotted_name(node) or "").split(".")[-1]


def _intent_type(node: ast.AST) -> Optional[str]:
    """Record type of a journal-intent dict literal
    (``{"t": "done", ...}``), else None."""
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            if _const_str(k) == "t" and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                return v.value
    return None


def _is_journal_flush(call: ast.Call) -> bool:
    """A direct group-commit: ``self._journal_append(...)`` (bare or
    attribute) or ``<x>._journal.append(...)``."""
    name = _last_name(call.func)
    if name == "_journal_append":
        return True
    if isinstance(call.func, ast.Attribute) and call.func.attr == "append":
        recv = _last_name(call.func.value)
        return recv in ("_journal", "journal")
    return False


def _registrations(mod: ModuleInfo) -> Dict[str, Tuple[bool, str, ast.AST]]:
    """{rpc_name: (fenced, handler_method_attr, key_node)} from the
    RpcServer(MASTER_SERVICE, {...}) registration — like contracts'
    `_master_registrations` but resolving the handler *method name*
    (through the `self._fenced(...)` wrapper) so the durability passes
    can analyze handler bodies."""
    out: Dict[str, Tuple[bool, str, ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and _last_name(node.func) == "RpcServer"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Dict)):
            continue
        if _last_name(node.args[0]) != "MASTER_SERVICE":
            continue
        for k, v in zip(node.args[1].keys, node.args[1].values):
            rpc = _const_str(k)
            if rpc is None:
                continue
            fenced = False
            if isinstance(v, ast.Call) and _last_name(v.func) == "_fenced" \
                    and v.args:
                fenced = True
                v = v.args[0]
            method = _last_name(v)
            if method:
                out[rpc] = (fenced, method, k)
    return out


def _thread_targets(cls: ast.ClassDef) -> Set[str]:
    """Method names handed to ``threading.Thread(target=self.X)``
    anywhere in the class — background entry points the fence audit
    must follow."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _last_name(node.func) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value,
                                                     ast.Attribute) \
                        and isinstance(kw.value.value, ast.Name) \
                        and kw.value.value.id == "self":
                    out.add(kw.value.attr)
    return out


class _EffectWalk:
    """Collects journal-relevant events of one statement in (approx)
    source order: ("dirty", node) for intent-record creation / durable
    mutation, ("flush", node) for group-commit, resolving one-level
    self-calls through `summaries` (the CallGraph fixpoint)."""

    def __init__(self, summaries: Dict[str, str]):
        self.summaries = summaries
        self.events: List[Tuple[str, ast.AST]] = []

    def collect(self, stmt: ast.AST) -> List[Tuple[str, ast.AST]]:
        self.events = []
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # the simulator walks the body statement-by-statement;
            # only the context expressions belong to the With itself
            for item in stmt.items:
                self._visit(item.context_expr)
        else:
            self._visit(stmt)
        return self.events

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.Call):
            if _is_journal_flush(node):
                # the intent dicts in its args are consumed by the
                # commit, not separate pending dirt
                self.events.append(("flush", node))
                return
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "append" and len(node.args) == 1 \
                        and _intent_type(node.args[0]) is not None:
                    self.events.append(("dirty", node))
                    return
                if func.attr in _SET_MUTATORS \
                        and _last_name(func.value) in _DURABLE_ATTRS:
                    self.events.append(("dirty", node))
                    return
                if isinstance(func.value, ast.Name) \
                        and func.value.id == "self":
                    eff = self.summaries.get(func.attr, _EFFECT_NONE)
                    if eff == _EFFECT_FLUSH:
                        self.events.append(("flush", node))
                    elif eff == _EFFECT_DIRTY:
                        self.events.append(("dirty", node))
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = None
                if isinstance(t, ast.Attribute):
                    attr = t.attr
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Attribute):
                    attr = t.value.attr
                if attr in _DURABLE_ATTRS:
                    self.events.append(("dirty", t))
        for child in ast.iter_child_nodes(node):
            self._visit(child)


class _WriteAheadSim(PathSimulator):
    """SC401 path walker.  State = (dirty, flushed):
    dirty — some path holds journal intent / durable mutation not yet
    group-committed; flushed — every path performed a commit."""

    def __init__(self, summaries: Dict[str, str]):
        self._walk = _EffectWalk(summaries)
        self.exit_state: Optional[Tuple[bool, bool]] = None
        self.dirty_exits: List[ast.AST] = []

    def initial(self):
        return (False, False)

    def join(self, a, b):
        return (a[0] or b[0], a[1] and b[1])

    def transfer(self, state, stmt):
        dirty, flushed = state
        for kind, _node in self._walk.collect(stmt):
            if kind == "flush":
                dirty, flushed = False, True
            else:
                dirty = True
        return (dirty, flushed)

    def _exit(self, state, node):
        self.exit_state = state if self.exit_state is None \
            else self.join(self.exit_state, state)
        if state[0]:
            self.dirty_exits.append(node)

    def on_return(self, state, node):
        self._exit(state, node)

    def on_end(self, state, node):
        self._exit(state, node)


def _method_summaries(cg: CallGraph) -> Dict[str, str]:
    """Fixpoint effect summary per method (what a call to it does to
    the caller's pending-journal state)."""
    summaries = {name: _EFFECT_NONE for name in cg.methods}
    for _ in range(len(cg.methods) + 2):
        changed = False
        for name, fn in cg.methods.items():
            sim = _WriteAheadSim(summaries)
            sim.run(fn)
            exit_state = sim.exit_state or (False, False)
            eff = _EFFECT_DIRTY if exit_state[0] else (
                _EFFECT_FLUSH if exit_state[1] else _EFFECT_NONE)
            if summaries[name] != eff:
                summaries[name] = eff
                changed = True
        if not changed:
            break
    return summaries


class DurabilityPass(AnalysisPass):
    name = "durability"
    codes = {
        "SC401": "RPC handler acks (returns) with journal-intent "
                 "records or durable mutations not group-committed on "
                 "every path (write-ahead: commit before ack)",
        "SC402": "durable-state mutation reachable from an unfenced "
                 "entry point (handler or background thread) with no "
                 "fence consultation on the path",
        "SC403": "stamped message field (gen/epoch/map_epoch) used "
                 "before mutation without a CAS/monotone comparison "
                 "(raw equality or no check)",
        "SC404": "journal record type without a replay arm / replay "
                 "arm or RECORD_TYPES entry without an appender",
        "SC405": "lock held across journal group-commit or collective "
                 "wait (heartbeats stall behind storage)",
        "SC406": "protocol model and RPC_CONTRACTS drift (transition "
                 "without a contract, or non-idempotent contract "
                 "missing from the model)",
    }

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for mod in project.modules:
            out.extend(self._module_passes(mod))
        out.extend(self._journal_round_trip(project))
        out.extend(self._model_anchoring(project))
        return out

    # -- SC401 / SC402 / SC405 (per master-service class) ----------------

    def _module_passes(self, mod: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        regs = _registrations(mod)
        for cls in [n for n in ast.walk(mod.tree)
                    if isinstance(n, ast.ClassDef)]:
            cls_methods = {s.name for s in cls.body
                           if isinstance(s, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
            cls_regs = {rpc: (fenced, meth, node)
                        for rpc, (fenced, meth, node) in regs.items()
                        if meth in cls_methods}
            if not cls_regs:
                continue
            cg = CallGraph(mod, cls)
            summaries = _method_summaries(cg)
            out.extend(self._write_ahead(mod, cg, cls_regs, summaries))
            out.extend(self._fence_coverage(mod, cls, cg, cls_regs,
                                            summaries))
            out.extend(self._lock_across_commit(mod, cls, cg))
        out.extend(self._staleness(mod))
        return out

    def _write_ahead(self, mod: ModuleInfo, cg: CallGraph,
                     regs: Dict[str, Tuple[bool, str, ast.AST]],
                     summaries: Dict[str, str]) -> List[Finding]:
        out: List[Finding] = []
        for rpc, (_fenced, meth, _node) in sorted(regs.items()):
            fn = cg.methods.get(meth)
            if fn is None:
                continue
            sim = _WriteAheadSim(summaries)
            sim.run(fn)
            for node in sim.dirty_exits:
                out.append(mod.finding(
                    "SC401",
                    f"handler `{meth}` (RPC `{rpc}`) can ack with "
                    "journal-intent records or durable mutations not "
                    "yet group-committed — `_journal_append` must "
                    "dominate every return (write-ahead: an acked "
                    "completion is never lost)", node))
        return out

    def _fence_coverage(self, mod: ModuleInfo, cls: ast.ClassDef,
                        cg: CallGraph,
                        regs: Dict[str, Tuple[bool, str, ast.AST]],
                        summaries: Dict[str, str]) -> List[Finding]:
        out: List[Finding] = []
        fence_aware = {
            name for name, fn in cg.methods.items()
            if any(isinstance(n, ast.Attribute)
                   and n.attr in _FENCE_ATTRS
                   for n in ast.walk(fn))}
        walk = _EffectWalk({})  # direct events only — no summaries
        mutators: Dict[str, ast.AST] = {}
        for name, fn in cg.methods.items():
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.stmt):
                    continue
                evs = [n for kind, n in walk.collect(stmt)
                       if kind == "dirty"]
                if evs:
                    mutators.setdefault(name, evs[0])
                    break
        entries: Dict[str, str] = {}
        for rpc, (fenced, meth, _node) in regs.items():
            if not fenced:
                entries.setdefault(meth, f"unfenced handler `{meth}` "
                                         f"(RPC `{rpc}`)")
        for meth in sorted(_thread_targets(cls)):
            entries.setdefault(meth, f"background thread `{meth}`")
        for entry, label in sorted(entries.items()):
            if entry in fence_aware:
                continue
            reachable = {entry} | cg.transitive_callees(entry)
            for m in sorted(reachable & set(mutators)):
                # a mutator that consults the fence itself (or through
                # a callee — _journal_append checks the fence flag
                # before any durable write) participates in the
                # protocol; the bug is mutation with no consultation
                if ({m} | cg.transitive_callees(m)) & fence_aware:
                    continue
                out.append(mod.finding(
                    "SC402",
                    f"durable-state mutation in `{m}` is reachable "
                    f"from {label} with no fence consultation on the "
                    "path — a superseded master would keep applying "
                    "it (SC312 only audits registration wrapping)",
                    mutators[m]))
        return out

    def _lock_across_commit(self, mod: ModuleInfo, cls: ast.ClassDef,
                            cg: CallGraph) -> List[Finding]:
        locks: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _last_name(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        locks.add(t.attr)
        if not locks:
            return []
        flushy = {name for name, fn in cg.methods.items()
                  if any(isinstance(n, ast.Call) and _is_journal_flush(n)
                         for n in ast.walk(fn))}
        reach_flush = cg.reaching(flushy) if flushy else set()
        out: List[Finding] = []

        def visit(node: ast.AST, held: Tuple[str, ...],
                  meth: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                add = []
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute) \
                            and isinstance(ctx.value, ast.Name) \
                            and ctx.value.id == "self" \
                            and ctx.attr in locks:
                        add.append(ctx.attr)
                for s in node.body:
                    visit(s, held + tuple(add), meth)
                return
            if isinstance(node, ast.Call) and held:
                name = _last_name(node.func)
                blocking = None
                if _is_journal_flush(node):
                    blocking = "journal group-commit"
                elif name in _COLLECTIVE_WAITS:
                    blocking = f"collective wait `{name}`"
                elif isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in reach_flush \
                        and node.func.attr != meth:
                    blocking = (f"`{node.func.attr}` (transitively "
                                "group-commits)")
                if blocking is not None:
                    out.append(mod.finding(
                        "SC405",
                        f"{blocking} while holding "
                        f"`self.{'`, `self.'.join(held)}` — commit "
                        "waits must run outside control-plane locks "
                        "or every heartbeat stalls behind storage",
                        node))
            for child in ast.iter_child_nodes(node):
                visit(child, held, meth)

        for name, fn in cg.methods.items():
            for stmt in fn.body:
                visit(stmt, (), name)
        return out

    # -- SC403 -----------------------------------------------------------

    def _staleness(self, mod: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for fn in [n for n in ast.walk(mod.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            out.extend(self._staleness_fn(mod, fn))
        return out

    @staticmethod
    def _stamp_read(node: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
        """(stamp_key, receiver_name) when `node` reads a stamped field
        — ``x.get("gen")`` / ``x["epoch"]`` — else None."""
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args \
                and _const_str(node.args[0]) in _STAMP_KEYS:
            recv = node.func.value
            return (_const_str(node.args[0]),
                    recv.id if isinstance(recv, ast.Name) else None)
        if isinstance(node, ast.Subscript) \
                and _const_str(node.slice) in _STAMP_KEYS:
            recv = node.value
            return (_const_str(node.slice),
                    recv.id if isinstance(recv, ast.Name) else None)
        return None

    def _staleness_fn(self, mod: ModuleInfo,
                      fn: ast.FunctionDef) -> List[Finding]:
        reads: List[Tuple[str, Optional[str], ast.AST]] = []
        tainted: Set[str] = set()
        receivers: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            sr = self._stamp_read(node)
            if sr is not None:
                reads.append((sr[0], sr[1], node))
                if sr[1]:
                    receivers.add(sr[1])
        if not reads:
            return []

        def has_stamp(sub: ast.AST) -> bool:
            for n in ast.walk(sub):
                if self._stamp_read(n) is not None:
                    return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        # taint names assigned from stamp reads (one forward pass)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and has_stamp(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)

        monotone = equality = cas = delegated = False
        mutating = False
        eq_node: Optional[ast.AST] = None
        walk = _EffectWalk({})
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if any(has_stamp(o) for o in operands):
                    for op in node.ops:
                        if isinstance(op, (ast.Lt, ast.LtE, ast.Gt,
                                           ast.GtE)):
                            monotone = True
                        elif isinstance(op, (ast.Eq, ast.NotEq)):
                            equality = True
                            eq_node = eq_node or node
            elif isinstance(node, ast.Call):
                name = _last_name(node.func)
                if name in _CAS_NAMES:
                    cas = True
                elif name in ("max", "min") and has_stamp(node):
                    monotone = True
                elif any(isinstance(a, ast.Name)
                         and (a.id in receivers or a.id in tainted)
                         for a in node.args):
                    delegated = True
            if isinstance(node, ast.stmt):
                if any(kind == "dirty"
                       for kind, _n in walk.collect(node)):
                    mutating = True
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    if any(isinstance(t, ast.Attribute) for t in targets) \
                            and has_stamp(node.value):
                        mutating = True  # latch write from a stamp
        if not mutating:
            return []
        out: List[Finding] = []
        keys = sorted({k for k, _r, _n in reads})
        if equality and not (monotone or cas):
            out.append(mod.finding(
                "SC403",
                f"`{fn.name}` validates stamped field(s) "
                f"{', '.join(keys)} with raw ==/!= equality before "
                "mutating — staleness checks must be CAS or monotone "
                "(>=): equality re-admits any replayed stamp",
                eq_node or fn))
        elif not (monotone or cas or equality or delegated):
            out.append(mod.finding(
                "SC403",
                f"`{fn.name}` reads stamped field(s) {', '.join(keys)} "
                "and mutates durable/latch state without any "
                "CAS/monotone staleness check (and without delegating "
                "the stamp to a validator)", reads[0][2]))
        return out

    # -- SC404 -----------------------------------------------------------

    @staticmethod
    def _journal_coupled(mod: ModuleInfo) -> bool:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "_journal_append":
                return True
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "RECORD_TYPES"
                            for t in node.targets):
                return True
        return False

    def _journal_round_trip(self, project: Project) -> List[Finding]:
        mods = [m for m in project.modules if self._journal_coupled(m)]
        if not mods:
            return []
        appended: Dict[str, Tuple[ModuleInfo, ast.AST]] = {}
        replayed: Dict[str, Tuple[ModuleInfo, ast.AST]] = {}
        declared: Optional[Set[str]] = None
        declared_at: Optional[Tuple[ModuleInfo, ast.AST]] = None
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Dict):
                    rt = _intent_type(node)
                    if rt is not None:
                        appended.setdefault(rt, (mod, node))
            tup = _module_tuple(mod, "RECORD_TYPES")
            if tup is not None:
                declared = set(tup)
                declared_at = (mod, mod.tree)
            for fn in [n for n in ast.walk(mod.tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]:
                t_names: Set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) \
                            and self._is_t_read(node.value):
                        t_names |= {t.id for t in node.targets
                                    if isinstance(t, ast.Name)}
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Compare):
                        continue
                    operands = [node.left] + list(node.comparators)
                    reads_t = any(
                        self._is_t_read(o)
                        or (isinstance(o, ast.Name) and o.id in t_names)
                        for o in operands)
                    if not reads_t:
                        continue
                    for o in operands:
                        c = _const_str(o)
                        if c is not None:
                            replayed.setdefault(c, (mod, node))
                        elif isinstance(o, (ast.Tuple, ast.List,
                                            ast.Set)):
                            for el in o.elts:
                                cs = _const_str(el)
                                if cs is not None:
                                    replayed.setdefault(cs, (mod, node))
        out: List[Finding] = []
        for rt in sorted(set(appended) - set(replayed)):
            mod, node = appended[rt]
            out.append(mod.finding(
                "SC404",
                f"journal record type `{rt}` is appended but no "
                "replay arm compares against it — recovery would "
                "silently drop it", node))
        for rt in sorted(set(replayed) - set(appended)):
            mod, node = replayed[rt]
            out.append(mod.finding(
                "SC404",
                f"replay arm handles record type `{rt}` but nothing "
                "appends it — dead recovery code or a renamed "
                "appender", node))
        if declared is not None and declared_at is not None:
            dmod, dnode = declared_at
            for rt in sorted(declared - set(appended)):
                out.append(dmod.finding(
                    "SC404",
                    f"RECORD_TYPES declares `{rt}` but nothing "
                    "appends it", dnode))
            for rt in sorted(set(appended) - declared):
                mod, node = appended[rt]
                out.append(mod.finding(
                    "SC404",
                    f"record type `{rt}` is appended but missing from "
                    "RECORD_TYPES — tooling that folds over the "
                    "declared set will not see it", node))
        return out

    @staticmethod
    def _is_t_read(node: ast.AST) -> bool:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args:
            return _const_str(node.args[0]) == "t"
        if isinstance(node, ast.Subscript):
            return _const_str(node.slice) == "t"
        return False

    # -- SC406 -----------------------------------------------------------

    def _model_anchoring(self, project: Project) -> List[Finding]:
        model_mod: Optional[ModuleInfo] = None
        for m in project.modules:
            if "analysis/model/" in m.relpath:
                if self._anchors(m) is not None:
                    model_mod = m
                    break
        contracts_mod: Optional[ModuleInfo] = None
        contracts: Optional[Dict[str, object]] = None
        for m in project.modules:
            got = ContractPass._contract_idempotency(m)
            if got is not None:
                contracts_mod, contracts = m, got
                break
        out: List[Finding] = []
        has_model_pkg = any("analysis/model/" in m.relpath
                            for m in project.modules)
        if model_mod is None:
            if has_model_pkg and contracts is not None:
                anchor = next(m for m in project.modules
                              if "analysis/model/" in m.relpath)
                out.append(anchor.finding(
                    "SC406",
                    "analysis/model/ defines no RPC_ANCHORS dict — "
                    "the protocol model must anchor its transitions "
                    "to RPC_CONTRACTS so it cannot rot from the "
                    "source", anchor.tree))
            return out
        anchors = self._anchors(model_mod) or {}
        transitions = {n.name[2:] for n in ast.walk(model_mod.tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and n.name.startswith("t_")}
        for key, (rpc, node) in sorted(anchors.items()):
            if key not in transitions:
                out.append(model_mod.finding(
                    "SC406",
                    f"RPC_ANCHORS names transition `{key}` but the "
                    f"model defines no `t_{key}` — the anchor points "
                    "at nothing", node))
            if contracts is not None and rpc not in contracts:
                out.append(model_mod.finding(
                    "SC406",
                    f"model transition `{key}` anchors RPC `{rpc}` "
                    "which has no RPC_CONTRACTS entry — the model "
                    "describes an RPC the engine does not declare",
                    node))
        if contracts is not None and contracts_mod is not None:
            anchored_rpcs = {rpc for rpc, _n in anchors.values()}
            for rpc, idem in sorted(contracts.items()):
                if idem is False and rpc not in anchored_rpcs:
                    out.append(model_mod.finding(
                        "SC406",
                        f"RPC `{rpc}` is classified idempotent=False "
                        "but no model transition anchors it — the "
                        "bounded-interleaving explorer is blind to a "
                        "mutating RPC (add a transition or an anchor)",
                        model_mod.tree))
        return out

    @staticmethod
    def _anchors(mod: ModuleInfo
                 ) -> Optional[Dict[str, Tuple[str, ast.AST]]]:
        """{transition: (rpc, key_node)} from the module-level
        RPC_ANCHORS dict literal, or None when absent."""
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "RPC_ANCHORS" \
                    and isinstance(stmt.value, ast.Dict):
                out: Dict[str, Tuple[str, ast.AST]] = {}
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    ks, vs = _const_str(k), _const_str(v)
                    if ks is not None and vs is not None:
                        out[ks] = (vs, k)
                return out
        return None
