"""scanner-check CLI.

    scanner-check [paths...]            # human output, exit 1 on findings
    scanner-check --json                # machine output (CI, bench.py)
    scanner-check --write-baseline      # accept current findings
    scanner-check --list-codes          # what the passes check

Invoked as `python tools/scanner_check.py`, the `scanner-check` console
script, or the tier-1 gate test
(tests/test_static_analysis.py::test_repo_is_clean).  Default target is
the scanner_tpu package of the repo the CLI runs from; default baseline
is tools/scanner_check_baseline.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from .core import (BaselineError, Finding, Project, find_repo_root,
                   load_baseline, split_findings, write_baseline)
from .tracer import TracerSafetyPass
from .concurrency import ConcurrencyPass
from .contracts import ContractPass
from .durability import DurabilityPass

DEFAULT_BASELINE = os.path.join("tools", "scanner_check_baseline.json")

# modules --changed always re-analyzes alongside the touched set: the
# cross-module passes (SC31x fence routing, SC404 journal round-trip,
# SC406 model anchoring) read these for context, so a restricted run
# reports the same findings for a touched module as a full run would
_CHANGED_COMPANIONS = (
    "scanner_tpu/engine/service.py",
    "scanner_tpu/engine/journal.py",
    "scanner_tpu/engine/shardmap.py",
    "scanner_tpu/engine/gang.py",
    "scanner_tpu/engine/controller.py",
    "scanner_tpu/engine/config.py",
    "scanner_tpu/analysis/model/protocol.py",
)


def all_passes(select: Optional[Sequence[str]] = None):
    """Every pass family — or, with `select` code prefixes, only the
    families owning a matching code (the shared-Project speed path:
    `--select SC2` must not pay for the tracer or contract walks)."""
    passes = [TracerSafetyPass(), ConcurrencyPass(), ContractPass(),
              DurabilityPass()]
    if select:
        passes = [p for p in passes
                  if any(code.startswith(s)
                         for code in p.codes for s in select)]
    return passes


def analyze(paths: Sequence[str], root: Optional[str] = None,
            select: Optional[Sequence[str]] = None
            ) -> "tuple[Project, List[Finding]]":
    """THE run protocol, shared by the CLI, bench.py, and the tests:
    build ONE Project shared by every pass family, seed findings with
    parse errors, run the (select-filtered) passes, sort."""
    project = Project(paths, root=root)
    findings: List[Finding] = list(project.parse_errors)
    for p in all_passes(select):
        findings.extend(p.run(project))
    if select:
        findings = [f for f in findings
                    if any(f.code.startswith(s) for s in select)]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return project, findings


def changed_paths(root: str) -> Optional[List[str]]:
    """Analysis targets for --changed: the working tree's touched
    scanner_tpu/*.py files (vs HEAD, plus untracked) together with the
    cross-module companion set.  Returns None when the analyzer itself
    (scanner_tpu/analysis/ or tools/) is among the changes — those
    affect every finding, so the caller falls back to a full run."""
    def git(*args: str) -> List[str]:
        try:
            res = subprocess.run(
                ["git", *args], cwd=root, capture_output=True,
                text=True, timeout=30, check=True)
        except Exception:  # noqa: BLE001 — no git ⇒ full run
            return []
        return [ln.strip() for ln in res.stdout.splitlines()
                if ln.strip()]

    changed = set(git("diff", "--name-only", "HEAD"))
    changed |= set(git("ls-files", "--others", "--exclude-standard"))
    if not changed and not os.path.isdir(os.path.join(root, ".git")):
        return None  # not a checkout — nothing to scope by
    touched = [c for c in changed
               if c.endswith(".py") and c.startswith("scanner_tpu/")]
    if any(c.startswith("scanner_tpu/analysis/") for c in touched) \
            or any(c.startswith("tools/") for c in changed):
        return None
    if not touched:
        return []
    targets = dict.fromkeys(list(touched) + [
        c for c in _CHANGED_COMPANIONS
        if os.path.exists(os.path.join(root, c))])
    return [os.path.join(root, c) for c in targets]


def run_analysis(paths: Sequence[str], root: Optional[str] = None,
                 select: Optional[Sequence[str]] = None) -> List[Finding]:
    """analyze() without the project — raw findings, suppression not
    yet applied."""
    return analyze(paths, root=root, select=select)[1]


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="scanner-check",
        description="scanner_tpu repo-native static analysis "
                    "(docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the repo's "
                         "scanner_tpu/ package)")
    ap.add_argument("--root", default=None,
                    help="repo root (docs/, tests/ context); default: "
                         "auto-detected from the first path")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default <root>/"
                         f"{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current unsuppressed findings into the "
                         "baseline (keeps existing justifications; new "
                         "entries need one before the file loads again)")
    ap.add_argument("--justification", default="TODO: justify",
                    help="justification recorded for NEW baseline "
                         "entries with --write-baseline")
    ap.add_argument("--select", action="append", default=None,
                    metavar="CODE",
                    help="only run/report codes with this prefix "
                         "(repeatable): --select SC2 --select SC301")
    ap.add_argument("--changed", action="store_true",
                    help="analyze only modules touched vs git (plus "
                         "the cross-module companion set); falls back "
                         "to a full run when the analyzer itself "
                         "changed")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-codes", action="store_true",
                    help="list finding codes and exit")
    args = ap.parse_args(argv)

    if args.list_codes:
        for p in all_passes():
            print(f"[{p.name}]")
            for code, desc in sorted(p.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    if args.paths:
        paths = args.paths
        root = args.root or find_repo_root(paths[0])
    else:
        root = args.root or find_repo_root(
            os.path.dirname(os.path.abspath(__file__)))
        paths = [os.path.join(root, "scanner_tpu")]

    restricted = False
    if args.changed:
        if args.write_baseline:
            print("scanner-check: --write-baseline cannot be combined "
                  "with --changed (a restricted run would erase "
                  "baseline entries outside it)", file=sys.stderr)
            return 2
        scoped = changed_paths(root)
        if scoped is not None:
            if not scoped:
                print("scanner-check: --changed: no scanner_tpu "
                      "modules touched")
                return 0
            paths = scoped
            restricted = True

    if args.write_baseline and args.select:
        # a selected subset cannot see the other codes' findings, so a
        # rewrite would silently drop their (justified) baseline entries
        print("scanner-check: --write-baseline cannot be combined with "
              "--select (it would erase baseline entries outside the "
              "selection)", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    try:
        baseline = {} if args.no_baseline else load_baseline(baseline_path)
    except BaselineError as e:
        print(f"scanner-check: baseline error: {e}", file=sys.stderr)
        return 2

    project, findings = analyze(paths, root=root, select=args.select)
    res = split_findings(project, findings, baseline)
    if args.select or restricted:
        # a selected/--changed run can't see the other codes'/files'
        # findings, so their baseline entries would all look stale —
        # don't claim they are
        res.stale_baseline = []

    if args.write_baseline:
        new = write_baseline(baseline_path,
                             res.unsuppressed + res.baselined,
                             previous=baseline,
                             justification=args.justification)
        print(f"scanner-check: baseline written to {baseline_path} "
              f"({len(res.unsuppressed) + len(res.baselined)} entries, "
              f"{new} new)")
        if new and args.justification.upper().startswith("TODO"):
            print("scanner-check: new entries carry a TODO justification "
                  "— edit them in or the baseline will not load",
                  file=sys.stderr)
        return 0

    counts: dict = {}
    for f in res.unsuppressed:
        counts[f.code] = counts.get(f.code, 0) + 1

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in res.unsuppressed],
            "counts": counts,
            "baselined": len(res.baselined),
            "inline_suppressed": len(res.inline_suppressed),
            "stale_baseline": res.stale_baseline,
            "files_analyzed": len(project.modules),
        }, indent=1))
    else:
        for f in res.unsuppressed:
            print(f.format())
        bits = [f"{len(project.modules)} files",
                f"{len(res.unsuppressed)} finding(s)"]
        if res.baselined:
            bits.append(f"{len(res.baselined)} baselined")
        if res.inline_suppressed:
            bits.append(f"{len(res.inline_suppressed)} suppressed inline")
        if res.stale_baseline:
            bits.append(f"{len(res.stale_baseline)} STALE baseline "
                        "entries (prune with --write-baseline)")
        print("scanner-check: " + ", ".join(bits))

    return 1 if res.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
