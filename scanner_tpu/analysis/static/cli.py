"""scanner-check CLI.

    scanner-check [paths...]            # human output, exit 1 on findings
    scanner-check --json                # machine output (CI, bench.py)
    scanner-check --write-baseline      # accept current findings
    scanner-check --list-codes          # what the passes check

Invoked as `python tools/scanner_check.py`, the `scanner-check` console
script, or the tier-1 gate test
(tests/test_static_analysis.py::test_repo_is_clean).  Default target is
the scanner_tpu package of the repo the CLI runs from; default baseline
is tools/scanner_check_baseline.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from .core import (BaselineError, Finding, Project, find_repo_root,
                   load_baseline, split_findings, write_baseline)
from .tracer import TracerSafetyPass
from .concurrency import ConcurrencyPass
from .contracts import ContractPass

DEFAULT_BASELINE = os.path.join("tools", "scanner_check_baseline.json")


def all_passes():
    return [TracerSafetyPass(), ConcurrencyPass(), ContractPass()]


def analyze(paths: Sequence[str], root: Optional[str] = None,
            select: Optional[Sequence[str]] = None
            ) -> "tuple[Project, List[Finding]]":
    """THE run protocol, shared by the CLI, bench.py, and the tests:
    build the Project, seed findings with parse errors, run every pass,
    optionally filter to code prefixes, sort.  Returns the project too
    (split_findings needs it for inline-suppression lookup)."""
    project = Project(paths, root=root)
    findings: List[Finding] = list(project.parse_errors)
    for p in all_passes():
        findings.extend(p.run(project))
    if select:
        findings = [f for f in findings
                    if any(f.code.startswith(s) for s in select)]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return project, findings


def run_analysis(paths: Sequence[str], root: Optional[str] = None,
                 select: Optional[Sequence[str]] = None) -> List[Finding]:
    """analyze() without the project — raw findings, suppression not
    yet applied."""
    return analyze(paths, root=root, select=select)[1]


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="scanner-check",
        description="scanner_tpu repo-native static analysis "
                    "(docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the repo's "
                         "scanner_tpu/ package)")
    ap.add_argument("--root", default=None,
                    help="repo root (docs/, tests/ context); default: "
                         "auto-detected from the first path")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default <root>/"
                         f"{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current unsuppressed findings into the "
                         "baseline (keeps existing justifications; new "
                         "entries need one before the file loads again)")
    ap.add_argument("--justification", default="TODO: justify",
                    help="justification recorded for NEW baseline "
                         "entries with --write-baseline")
    ap.add_argument("--select", action="append", default=None,
                    metavar="CODE",
                    help="only run/report codes with this prefix "
                         "(repeatable): --select SC2 --select SC301")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-codes", action="store_true",
                    help="list finding codes and exit")
    args = ap.parse_args(argv)

    if args.list_codes:
        for p in all_passes():
            print(f"[{p.name}]")
            for code, desc in sorted(p.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    if args.paths:
        paths = args.paths
        root = args.root or find_repo_root(paths[0])
    else:
        root = args.root or find_repo_root(
            os.path.dirname(os.path.abspath(__file__)))
        paths = [os.path.join(root, "scanner_tpu")]

    if args.write_baseline and args.select:
        # a selected subset cannot see the other codes' findings, so a
        # rewrite would silently drop their (justified) baseline entries
        print("scanner-check: --write-baseline cannot be combined with "
              "--select (it would erase baseline entries outside the "
              "selection)", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    try:
        baseline = {} if args.no_baseline else load_baseline(baseline_path)
    except BaselineError as e:
        print(f"scanner-check: baseline error: {e}", file=sys.stderr)
        return 2

    project, findings = analyze(paths, root=root, select=args.select)
    res = split_findings(project, findings, baseline)
    if args.select:
        # a selected run can't see the other codes' findings, so their
        # baseline entries would all look stale — don't claim they are
        res.stale_baseline = []

    if args.write_baseline:
        new = write_baseline(baseline_path,
                             res.unsuppressed + res.baselined,
                             previous=baseline,
                             justification=args.justification)
        print(f"scanner-check: baseline written to {baseline_path} "
              f"({len(res.unsuppressed) + len(res.baselined)} entries, "
              f"{new} new)")
        if new and args.justification.upper().startswith("TODO"):
            print("scanner-check: new entries carry a TODO justification "
                  "— edit them in or the baseline will not load",
                  file=sys.stderr)
        return 0

    counts: dict = {}
    for f in res.unsuppressed:
        counts[f.code] = counts.get(f.code, 0) + 1

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in res.unsuppressed],
            "counts": counts,
            "baselined": len(res.baselined),
            "inline_suppressed": len(res.inline_suppressed),
            "stale_baseline": res.stale_baseline,
            "files_analyzed": len(project.modules),
        }, indent=1))
    else:
        for f in res.unsuppressed:
            print(f.format())
        bits = [f"{len(project.modules)} files",
                f"{len(res.unsuppressed)} finding(s)"]
        if res.baselined:
            bits.append(f"{len(res.baselined)} baselined")
        if res.inline_suppressed:
            bits.append(f"{len(res.inline_suppressed)} suppressed inline")
        if res.stale_baseline:
            bits.append(f"{len(res.stale_baseline)} STALE baseline "
                        "entries (prune with --write-baseline)")
        print("scanner-check: " + ", ".join(bits))

    return 1 if res.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
