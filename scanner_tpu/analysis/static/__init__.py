"""Repo-native static analysis (scanner-check).

Four pass families over the scanner_tpu source:

  * tracer.py      — SC101–SC105: tracer safety + shape-stable dispatch
  * concurrency.py — SC201–SC203: lock order, blocking-under-lock,
                     unguarded shared writes
  * contracts.py   — SC301–SC307: metric/env/config/fault/RPC contracts
  * durability.py  — SC401–SC406: write-ahead/fencing data-flow and
                     journal round-trip discipline, plus anchoring of
                     the analysis.model protocol model to RPC_CONTRACTS

Run via `python tools/scanner_check.py`, the `scanner-check` console
script, or programmatically::

    from scanner_tpu.analysis.static import run_analysis
    findings = run_analysis(["scanner_tpu/"])

The tier-1 gate (tests/test_static_analysis.py) fails on any finding
not inline-suppressed or baselined with a justification.  Docs:
docs/static-analysis.md.
"""

from .core import (AnalysisPass, BaselineError, CallGraph, Finding,
                   ModuleInfo, PathSimulator, Project, find_repo_root,
                   load_baseline, split_findings, write_baseline)
from .tracer import TracerSafetyPass
from .concurrency import ConcurrencyPass
from .contracts import ContractPass
from .durability import DurabilityPass
from .cli import (DEFAULT_BASELINE, all_passes, analyze, changed_paths,
                  main, run_analysis)

__all__ = [
    "AnalysisPass", "BaselineError", "CallGraph", "Finding",
    "ModuleInfo", "PathSimulator", "Project",
    "TracerSafetyPass", "ConcurrencyPass", "ContractPass",
    "DurabilityPass",
    "find_repo_root", "load_baseline", "split_findings",
    "write_baseline", "all_passes", "analyze", "changed_paths",
    "run_analysis", "main", "DEFAULT_BASELINE",
]
