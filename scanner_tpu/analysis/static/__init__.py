"""Repo-native static analysis (scanner-check).

Three pass families over the scanner_tpu source:

  * tracer.py      — SC101–SC105: tracer safety + shape-stable dispatch
  * concurrency.py — SC201–SC203: lock order, blocking-under-lock,
                     unguarded shared writes
  * contracts.py   — SC301–SC307: metric/env/config/fault/RPC contracts

Run via `python tools/scanner_check.py`, the `scanner-check` console
script, or programmatically::

    from scanner_tpu.analysis.static import run_analysis
    findings = run_analysis(["scanner_tpu/"])

The tier-1 gate (tests/test_static_analysis.py) fails on any finding
not inline-suppressed or baselined with a justification.  Docs:
docs/static-analysis.md.
"""

from .core import (AnalysisPass, BaselineError, Finding, ModuleInfo,
                   Project, find_repo_root, load_baseline,
                   split_findings, write_baseline)
from .tracer import TracerSafetyPass
from .concurrency import ConcurrencyPass
from .contracts import ContractPass
from .cli import (DEFAULT_BASELINE, all_passes, analyze, main,
                  run_analysis)

__all__ = [
    "AnalysisPass", "BaselineError", "Finding", "ModuleInfo", "Project",
    "TracerSafetyPass", "ConcurrencyPass", "ContractPass",
    "find_repo_root", "load_baseline", "split_findings",
    "write_baseline", "all_passes", "analyze", "run_analysis", "main",
    "DEFAULT_BASELINE",
]
