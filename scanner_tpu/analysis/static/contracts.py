"""Contract lints: code ↔ docs ↔ wiring drift (SC301–SC307).

Operational surfaces (the metric catalog, env-var and config knobs,
fault-injection sites, the RPC method table) are contracts: dashboards,
deploy manifests, chaos plans, and runbooks are written against them.
Nothing but convention keeps them in sync with the source — so these
passes make each one checkable:

  SC301  metric series registered in source but missing from the
         docs/observability.md catalog (or catalogued but gone)
  SC302  metric naming contract: `scanner_tpu_[a-z0-9_]+`, counters end
         `_total`, every series carries a help string
  SC303  `SCANNER_TPU_*` env var read in source but undocumented under
         docs/ (or documented but never read)
  SC304  config `[section] key` read that `config.default_config()`
         doesn't declare, or a declared key no doc page mentions
  SC305  fault-injection drift: `faults.inject("site")` literal not in
         `faults.SITES`, a SITES entry with no wired hook, or a
         NAMED_PLANS clause naming an unknown site
  SC306  RPC drift: a client `.call("Method")` no server registers, or
         a registered handler nothing in the repo ever invokes
  SC307  RPC classification: every registered handler needs an
         `RPC_CONTRACTS` entry (timeout class + idempotency — what the
         retry/backoff layer is allowed to do with it)
  SC308  alert-rule contract drift: the health engine's DEFAULT_RULES
         names and the docs/observability.md default-ruleset table may
         not drift (both directions; the table is delimited by
         `default-alert-rules:begin/end` markers), and the `[alerts]`
         config section must declare exactly the keys
         health.CONFIG_KEYS accepts
  SC309  cost-model / efficiency-series drift: every device (TPU)
         kernel registered under `kernels/` must declare a `cost()`
         descriptor hook (roofline attribution, util/coststats.py),
         and coststats' EFFICIENCY_SERIES tuple, the series it
         actually registers, and the marker-delimited efficiency table
         in docs/observability.md (`efficiency-series:begin/end`) may
         not drift — all three pairings, both directions
  SC310  frame-cache contract drift (engine/framecache.py): the
         FRAMECACHE_SERIES tuple, the series the module actually
         registers, and the marker-delimited table in
         docs/observability.md (`framecache-series:begin/end`) may not
         drift (all pairings, both directions); and the `[perf]`
         frame_cache_* config keys config.default_config() declares
         must be exactly framecache.CONFIG_KEYS (both directions)
  SC311  remediation contract drift (engine/controller.py): every
         DEFAULT_PLAYBOOKS entry must bind an alert that exists in
         health.DEFAULT_RULES; the playbook names and alert bindings
         must match the marker-delimited playbook matrix in
         docs/robustness.md (`remediation-playbooks:begin/end`), both
         directions; and the `[remediation]` config keys
         config.default_config() declares must be exactly
         controller.CONFIG_KEYS (both directions)
  SC312  generation-fence routing drift (engine/service.py +
         engine/journal.py): every RPC_CONTRACTS entry classified
         `idempotent=False` must register its MASTER_SERVICE handler
         wrapped in the generation-fence helper (`self._fenced(...)`),
         and every fence-wrapped registration must be classified
         non-idempotent — a mutating handler outside the fence lets a
         superseded (stale) master keep accepting mutations; and the
         `[robustness]` journal_* config keys config.default_config()
         declares must be exactly journal.CONFIG_KEYS (both
         directions)
  SC313  gang contract drift (engine/service.py + engine/gang.py,
         extending SC312): every `Gang*` RPC_CONTRACTS entry must be
         classified `idempotent=False` AND register its master handler
         through the generation fence, in both directions — gang RPCs
         mutate scheduling state and additionally carry the
         (gang_id, epoch) fence, so an unfenced or misclassified gang
         handler would let a stale master (or a blind retry)
         double-apply completion/abort traffic; and the `[gang]`
         config keys config.default_config() declares, the
         gang.CONFIG_KEYS tuple, and the `[gang] <key>` rows in
         docs/guide.md may not drift (all pairings, both directions)
  SC314  cross-host time contract drift (util/clocksync.py +
         engine/gang.py): clocksync.CLOCKSYNC_SERIES and
         gang.GANG_PHASE_SERIES must match the series each module
         registers, and their union must match the marker-delimited
         table in docs/observability.md
         (`clocksync-series:begin/end`), both directions; the
         `gang.*` span names engine/gang.py opens must match the
         `gang-phase-taxonomy:begin/end` table in
         docs/observability.md, both directions — an undocumented
         phase span (or a documented phantom) makes merged-timeline
         skew triage lie; and the `[trace]` clock keys
         config.default_config() declares (all but the tracing-owned
         `enabled`) must be exactly clocksync.CONFIG_KEYS (both
         directions)
  SC315  sharded gang data-plane drift (engine/gang.py):
         gang.GANG_SHARD_SERIES must match the `_shard_`-named series
         the module registers AND the marker-delimited
         `gang-shard-series:begin/end` table in docs/observability.md
         (all pairings, both directions); and the sharded path's
         config gates (`[gang] sharded` / `halo_exchange`) must
         travel with the data plane — gang.CONFIG_KEYS and
         config.default_config() must declare both whenever
         GANG_SHARD_SERIES exists, and a gate without a data plane is
         flagged too (both directions)
  SC316  sharded control-plane drift (engine/shardmap.py +
         engine/service.py): shardmap.SHARD_SERIES must match the
         series the module registers AND the marker-delimited
         `shard-series:begin/end` table in docs/observability.md
         (all pairings, both directions); the `[control]` config
         keys config.default_config() declares must be exactly
         shardmap.CONFIG_KEYS (both directions); and the
         shard-routed RPC surface may not drift (extending SC312):
         every service.SHARD_ROUTED_RPCS method must be classified
         `idempotent=False` and register its master handler through
         the generation fence, and every idempotent=False contract
         must be shard-routed — a mutating RPC missing from the
         routing tuple would land on the dial-time shard regardless
         of which master owns the bulk it mutates
  SC317  whole-pipeline fusion drift (graph/fusion.py):
         fusion.FUSION_SERIES must match the series the module
         registers AND the marker-delimited
         `fusion-series:begin/end` table in docs/observability.md
         (all pairings, both directions); the `[perf] fusion_*`
         config keys config.default_config() declares must be
         exactly fusion.CONFIG_KEYS (both directions); and a kernel
         class overriding `execute_traced` (declaring itself
         trace-composable for fusion) without a `cost()` hook is
         flagged (extends SC309) — the planner's fusability gate
         keys on cost(), so such a kernel silently never fuses
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisPass, Finding, ModuleInfo, Project
from .tracer import dotted_name

_SERIES_RE = re.compile(r"scanner_tpu_[a-z0-9_]*[a-z0-9]")
_SERIES_OK_RE = re.compile(r"scanner_tpu_[a-z0-9_]+\Z")
_ENV_RE = re.compile(r"SCANNER_TPU_[A-Z0-9_]*[A-Z0-9]")
# prometheus exposition suffixes a doc may legitimately mention
_EXPOSITION_SUFFIXES = ("_bucket", "_sum", "_count")

_REG_KINDS = ("counter", "gauge", "histogram")


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _read_doc(project: Project, name: str) -> str:
    p = os.path.join(project.root, "docs", name)
    if os.path.exists(p):
        with open(p, encoding="utf-8") as f:
            return f.read()
    return ""


# ---------------------------------------------------------------------------
# metric registrations
# ---------------------------------------------------------------------------

class _Registration:
    def __init__(self, mod: ModuleInfo, node: ast.Call, kind: str,
                 name: Optional[str], help_arg: Optional[ast.AST]):
        self.mod = mod
        self.node = node
        self.kind = kind
        self.name = name
        self.help_arg = help_arg


def _metric_registrations(mod: ModuleInfo) -> List[_Registration]:
    out = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REG_KINDS):
            continue
        base = node.func.value
        if isinstance(base, ast.Call):
            base_ok = (dotted_name(base.func) or "").split(".")[-1] \
                == "registry"
        else:
            # module-level singleton idiom: _REGISTRY.gauge(...)
            base_ok = (dotted_name(base) or "").split(".")[-1] \
                .lower().lstrip("_") == "registry"
        if not base_ok:
            continue
        name = _const_str(node.args[0]) if node.args else None
        help_arg = node.args[1] if len(node.args) > 1 else None
        out.append(_Registration(mod, node, node.func.attr, name,
                                 help_arg))
    return out


# ---------------------------------------------------------------------------
# env reads
# ---------------------------------------------------------------------------

def _env_reads(mod: ModuleInfo) -> List[Tuple[str, ast.AST]]:
    """(var, node) for every SCANNER_TPU_* read through os.environ /
    environ / env (.get / [] / .pop)."""
    out: List[Tuple[str, ast.AST]] = []

    def is_env_base(e: ast.AST) -> bool:
        d = dotted_name(e) or ""
        return d.split(".")[-1] in ("environ",) or d in ("env",)

    for node in ast.walk(mod.tree):
        var: Optional[str] = None
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in ("get", "pop") \
                and is_env_base(node.func.value) and node.args:
            var = _const_str(node.args[0])
        elif isinstance(node, ast.Subscript) and is_env_base(node.value):
            var = _const_str(node.slice)
        if var and _ENV_RE.fullmatch(var):
            out.append((var, node))
    return out


# ---------------------------------------------------------------------------
# config reads
# ---------------------------------------------------------------------------

def _default_config_keys(mod: ModuleInfo) -> Set[Tuple[str, str]]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "default_config":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and isinstance(
                        sub.value, ast.Dict):
                    keys: Set[Tuple[str, str]] = set()
                    for sk, sv in zip(sub.value.keys, sub.value.values):
                        sec = _const_str(sk)
                        if sec is None or not isinstance(sv, ast.Dict):
                            continue
                        for kk in sv.keys:
                            k = _const_str(kk)
                            if k is not None:
                                keys.add((sec, k))
                    return keys
    return set()


def _config_reads(mod: ModuleInfo) -> List[Tuple[str, str, ast.AST]]:
    """(section, key, node) for config dict reads:
    cfg["sec"]["key"], cfg.get("sec", {}).get("key", d), and one level
    of local aliasing (n = cfg["sec"]; n.get("key"))."""
    out: List[Tuple[str, str, ast.AST]] = []

    def is_cfg_base(e: ast.AST) -> bool:
        if isinstance(e, ast.Attribute) and e.attr == "config":
            return True
        return isinstance(e, ast.Name) and e.id in ("cfg", "config")

    def section_of(e: ast.AST) -> Optional[str]:
        """'storage' if e is <cfg-base>["storage"] or
        <cfg-base>.get("storage", ...)"""
        if isinstance(e, ast.Subscript) and is_cfg_base(e.value):
            return _const_str(e.slice)
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
                and e.func.attr in ("get", "setdefault") \
                and is_cfg_base(e.func.value) and e.args:
            return _const_str(e.args[0])
        return None

    for fn in [n for n in ast.walk(mod.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        aliases: Dict[str, str] = {}
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                sec = section_of(sub.value)
                if sec is not None:
                    aliases[sub.targets[0].id] = sec

        def base_section(e: ast.AST) -> Optional[str]:
            sec = section_of(e)
            if sec is not None:
                return sec
            if isinstance(e, ast.Name):
                return aliases.get(e.id)
            return None

        for sub in ast.walk(fn):
            if isinstance(sub, ast.Subscript):
                sec = base_section(sub.value)
                key = _const_str(sub.slice)
                if sec is not None and key is not None:
                    out.append((sec, key, sub))
            elif isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute) and sub.func.attr == "get" \
                    and sub.args:
                sec = base_section(sub.func.value)
                key = _const_str(sub.args[0])
                if sec is not None and key is not None:
                    out.append((sec, key, sub))
    return out


# ---------------------------------------------------------------------------
# fault sites
# ---------------------------------------------------------------------------

def _module_tuple(mod: ModuleInfo, name: str) -> Optional[List[str]]:
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            vals = [_const_str(e) for e in stmt.value.elts]
            return [v for v in vals if v is not None]
    return None


def _module_dict_keys(mod: ModuleInfo, name: str) -> Optional[Set[str]]:
    """String keys of a module-level dict assignment whose VALUES may be
    arbitrary expressions (faults._EXC maps exc names to constructors —
    _module_str_dict cannot read it)."""
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, ast.Dict):
            keys = {_const_str(k) for k in stmt.value.keys}
            return {k for k in keys if k is not None}
    return None


def _module_str_dict(mod: ModuleInfo, name: str
                     ) -> Optional[Dict[str, str]]:
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, ast.Dict):
            out = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                ks, vs = _const_str(k), _const_str(v)
                if ks is not None and vs is not None:
                    out[ks] = vs
            return out
    return None


# ---------------------------------------------------------------------------
# rpc surface
# ---------------------------------------------------------------------------

def _rpc_registrations(mod: ModuleInfo) -> List[Tuple[str, ast.AST]]:
    """(method_name, dict_key_node) from RpcServer(service, {...})."""
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and (dotted_name(node.func) or "").split(".")[-1] \
                == "RpcServer" and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Dict):
            for k in node.args[1].keys:
                name = _const_str(k)
                if name is not None:
                    out.append((name, k))
    return out


def _rpc_invocations(mod: ModuleInfo) -> List[Tuple[str, ast.AST]]:
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in ("call", "try_call") and node.args:
            name = _const_str(node.args[0])
            if name is not None:
                out.append((name, node))
    return out


_AUX_CALL_RE = re.compile(r"\.(?:try_)?call\(\s*['\"]([A-Za-z_][\w]*)")


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

class ContractPass(AnalysisPass):
    name = "contracts"
    codes = {
        "SC301": "metric series out of sync with docs/observability.md",
        "SC302": "metric naming/help contract violation",
        "SC303": "SCANNER_TPU_* env var out of sync with docs/",
        "SC304": "config key read undeclared or undocumented",
        "SC305": "fault-injection site drift (SITES vs wired hooks)",
        "SC306": "RPC method drift (called vs registered)",
        "SC307": "RPC handler missing RPC_CONTRACTS classification",
        "SC308": "alert-rule drift (DEFAULT_RULES vs docs vs [alerts])",
        "SC309": "cost-model / efficiency-series drift (kernel cost "
                 "hooks, EFFICIENCY_SERIES, docs efficiency table)",
        "SC310": "frame-cache contract drift (FRAMECACHE_SERIES, docs "
                 "framecache table, [perf] frame_cache_* config keys)",
        "SC311": "remediation contract drift (DEFAULT_PLAYBOOKS vs "
                 "health rules vs docs playbook matrix vs "
                 "[remediation] config keys)",
        "SC312": "generation-fence routing drift (idempotent=False "
                 "RPC_CONTRACTS entries vs _fenced-wrapped master "
                 "handlers vs [robustness] journal config keys)",
        "SC313": "gang contract drift (Gang* RPC_CONTRACTS entries "
                 "must be non-idempotent + fence-wrapped; [gang] "
                 "config keys vs gang.CONFIG_KEYS vs docs/guide.md "
                 "rows)",
        "SC314": "cross-host time contract drift (CLOCKSYNC_SERIES + "
                 "GANG_PHASE_SERIES vs registrations vs docs "
                 "clocksync-series table; gang.* span names vs the "
                 "gang-phase-taxonomy table; [trace] clock keys vs "
                 "clocksync.CONFIG_KEYS)",
        "SC315": "sharded gang data-plane drift (GANG_SHARD_SERIES vs "
                 "gang registrations vs docs gang-shard-series table; "
                 "[gang] sharded/halo_exchange gates vs the data "
                 "plane)",
        "SC316": "sharded control-plane drift (SHARD_SERIES vs "
                 "shardmap registrations vs docs shard-series table; "
                 "[control] keys vs shardmap.CONFIG_KEYS; "
                 "SHARD_ROUTED_RPCS vs idempotent=False + "
                 "fence-wrapped master handlers)",
        "SC317": "whole-pipeline fusion drift (FUSION_SERIES vs "
                 "fusion registrations vs docs fusion-series table; "
                 "[perf] fusion_* keys vs fusion.CONFIG_KEYS; "
                 "execute_traced overrides without a cost() hook)",
    }

    def run(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        out.extend(self._metrics(project))
        out.extend(self._env_vars(project))
        out.extend(self._config_keys(project))
        out.extend(self._fault_sites(project))
        out.extend(self._rpc_surface(project))
        out.extend(self._alert_rules(project))
        out.extend(self._cost_model(project))
        out.extend(self._frame_cache(project))
        out.extend(self._remediation(project))
        out.extend(self._fence_routing(project))
        out.extend(self._gang_contract(project))
        out.extend(self._clocksync_contract(project))
        out.extend(self._gang_shard_contract(project))
        out.extend(self._shard_contract(project))
        out.extend(self._fusion_contract(project))
        return out

    # -- SC301 / SC302 ---------------------------------------------------

    def _metrics(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        doc = _read_doc(project, "observability.md")
        doc_names = {n for n in _SERIES_RE.findall(doc)}
        registered: Dict[str, _Registration] = {}
        for mod in project.modules:
            for reg in _metric_registrations(mod):
                if reg.name is None:
                    # dynamic name: can't check statically — flag it,
                    # the whole catalog idea depends on literal names
                    out.append(mod.finding(
                        "SC302",
                        f"{reg.kind}() with a non-literal series name — "
                        "series must be static so the catalog lint can "
                        "see them", reg.node))
                    continue
                registered.setdefault(reg.name, reg)
                if not _SERIES_OK_RE.fullmatch(reg.name):
                    out.append(mod.finding(
                        "SC302",
                        f"series `{reg.name}` does not match "
                        "scanner_tpu_[a-z0-9_]+", reg.node))
                elif reg.kind == "counter" \
                        and not reg.name.endswith("_total"):
                    out.append(mod.finding(
                        "SC302",
                        f"counter `{reg.name}` should end `_total`",
                        reg.node))
                help_str = _const_str(reg.help_arg)
                if help_str is None or not help_str.strip():
                    out.append(mod.finding(
                        "SC302",
                        f"series `{reg.name}` lacks a help string",
                        reg.node))
                if doc and reg.name not in doc_names:
                    out.append(mod.finding(
                        "SC301",
                        f"series `{reg.name}` is not catalogued in "
                        "docs/observability.md", reg.node))
        if doc and registered:
            base_doc_names = set()
            for n in doc_names:
                for suf in _EXPOSITION_SUFFIXES:
                    if n.endswith(suf) and n[:-len(suf)] in doc_names:
                        break
                else:
                    base_doc_names.add(n)
            for name in sorted(base_doc_names - set(registered)):
                for suf in _EXPOSITION_SUFFIXES:
                    if name.endswith(suf) and name[:-len(suf)] \
                            in registered:
                        break
                else:
                    out.append(Finding(
                        code="SC301",
                        message=f"docs/observability.md catalogues "
                                f"`{name}` but no source registers it",
                        path="docs/observability.md", line=1, scope="",
                        snippet=name))
        return out

    # -- SC303 -----------------------------------------------------------

    def _env_vars(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        docs = project.docs_text()
        doc_vars = set(_ENV_RE.findall(docs))
        read_vars: Set[str] = set()
        for mod in project.modules:
            for var, node in _env_reads(mod):
                read_vars.add(var)
                if docs and var not in doc_vars:
                    out.append(mod.finding(
                        "SC303",
                        f"env var `{var}` is read here but documented "
                        "nowhere under docs/ — knobs nobody can find "
                        "don't exist", node))
        if docs and read_vars:
            # vars also appear in code as manifest WRITES (deploy.py) and
            # plain mentions; only flag doc vars never read anywhere in
            # the analyzed source or auxiliary text
            aux = project.aux_source_text() + "".join(
                m.source for m in project.modules)
            for var in sorted(doc_vars - read_vars):
                if var not in aux:
                    out.append(Finding(
                        code="SC303",
                        message=f"docs mention env var `{var}` but "
                                "nothing reads it",
                        path="docs", line=1, scope="", snippet=var))
        return out

    # -- SC304 -----------------------------------------------------------

    def _config_keys(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if cfg_mod is None:
            return out
        declared = _default_config_keys(cfg_mod)
        docs = project.docs_text()
        # reads anywhere in the analyzed tree must be declared keys
        # (master_address-style alternates must still be declared or
        # documented)
        declared_keys = {k for _s, k in declared}
        for mod in project.modules:
            for sec, key, node in _config_reads(mod):
                if (sec, key) in declared:
                    continue
                if key in declared_keys:
                    continue  # cross-section helper access patterns
                if docs and re.search(rf"\b{re.escape(key)}\b", docs):
                    continue  # undeclared but documented alternate
                out.append(mod.finding(
                    "SC304",
                    f"config read `[{sec}] {key}` is neither declared "
                    "in config.default_config() nor documented under "
                    "docs/", node))
        if docs:
            for sec, key in sorted(declared):
                if not re.search(rf"\b{re.escape(key)}\b", docs):
                    out.append(cfg_mod.finding(
                        "SC304",
                        f"config key `[{sec}] {key}` is declared in "
                        "default_config() but no docs/ page mentions it",
                        cfg_mod.tree))
        return out

    # -- SC305 -----------------------------------------------------------

    def _fault_sites(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        fmod = project.module("util/faults.py")
        if fmod is None:
            return out
        sites = _module_tuple(fmod, "SITES")
        if not sites:
            return out
        site_set = set(sites)
        hooked: Set[str] = set()
        for mod in project.modules:
            if mod is fmod:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) \
                        and (dotted_name(node.func) or "").split(".")[-1] \
                        == "inject" and node.args:
                    site = _const_str(node.args[0])
                    if site is None:
                        continue
                    hooked.add(site)
                    if site not in site_set:
                        out.append(mod.finding(
                            "SC305",
                            f"faults.inject({site!r}) names a site "
                            "missing from faults.SITES — install() will "
                            "reject every plan targeting it", node))
        for site in sites:
            if site not in hooked:
                out.append(fmod.finding(
                    "SC305",
                    f"faults.SITES entry `{site}` has no wired "
                    "inject() hook — plans targeting it arm nothing "
                    "and chaos tests pass vacuously", fmod.tree))
        plans = _module_str_dict(fmod, "NAMED_PLANS") or {}
        data_sites = _module_tuple(fmod, "DATA_SITES") or []
        # clause-level validation beyond the site name: a canned plan
        # with a typo'd mode or an exc= key the _EXC table doesn't
        # construct would only fail when someone finally runs it
        modes = set(_module_tuple(fmod, "MODES") or ())
        exc_keys = _module_dict_keys(fmod, "_EXC")
        for name, plan in plans.items():
            for clause in plan.split(";"):
                fields = clause.strip().split(":")
                site = fields[0]
                if site and site not in site_set:
                    out.append(fmod.finding(
                        "SC305",
                        f"NAMED_PLANS[{name!r}] targets unknown site "
                        f"`{site}`", fmod.tree))
                if len(fields) > 1 and modes and fields[1] not in modes:
                    out.append(fmod.finding(
                        "SC305",
                        f"NAMED_PLANS[{name!r}] uses unknown mode "
                        f"`{fields[1]}` (known: "
                        f"{', '.join(sorted(modes))})", fmod.tree))
                for f in fields[2:]:
                    k, sep, v = f.partition("=")
                    if sep and k == "exc" and exc_keys is not None \
                            and v not in exc_keys:
                        out.append(fmod.finding(
                            "SC305",
                            f"NAMED_PLANS[{name!r}] names unknown "
                            f"exc `{v}` — parse_plan will reject the "
                            "plan at arm time", fmod.tree))
        for site in data_sites:
            if site not in site_set:
                out.append(fmod.finding(
                    "SC305",
                    f"DATA_SITES entry `{site}` is not in SITES",
                    fmod.tree))
        return out

    # -- SC308 -----------------------------------------------------------

    _ALERT_DOC_BLOCK_RE = re.compile(
        r"<!--\s*default-alert-rules:begin\s*-->(.*?)"
        r"<!--\s*default-alert-rules:end\s*-->", re.S)
    _ALERT_DOC_NAME_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`", re.M)

    @staticmethod
    def _default_rule_names(mod: ModuleInfo
                            ) -> Optional[List[Tuple[str, ast.AST]]]:
        """(name, node) per element of the module-level DEFAULT_RULES
        tuple — the literal `name=` kwarg (or first positional string)
        of each rule constructor call."""
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "DEFAULT_RULES" \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)):
                out: List[Tuple[str, ast.AST]] = []
                for el in stmt.value.elts:
                    if not isinstance(el, ast.Call):
                        continue
                    name = None
                    for kw in el.keywords:
                        if kw.arg == "name":
                            name = _const_str(kw.value)
                    if name is None and el.args:
                        name = _const_str(el.args[0])
                    if name is not None:
                        out.append((name, el))
                return out
        return None

    def _alert_rules(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        hmod = project.module("util/health.py")
        if hmod is None:
            return out
        rules = self._default_rule_names(hmod)
        doc = _read_doc(project, "observability.md")
        if rules:
            block = self._ALERT_DOC_BLOCK_RE.search(doc) if doc else None
            if doc and block is None:
                out.append(hmod.finding(
                    "SC308",
                    "health.DEFAULT_RULES exists but docs/"
                    "observability.md has no default-alert-rules "
                    "marker table (<!-- default-alert-rules:begin/end "
                    "-->) — operators cannot see what alerts by "
                    "default", hmod.tree))
            elif block is not None:
                doc_names = set(
                    self._ALERT_DOC_NAME_RE.findall(block.group(1)))
                for name, node in rules:
                    if name not in doc_names:
                        out.append(hmod.finding(
                            "SC308",
                            f"default alert rule `{name}` is missing "
                            "from the docs/observability.md "
                            "default-ruleset table", node))
                for name in sorted(doc_names
                                   - {n for n, _ in rules}):
                    out.append(Finding(
                        code="SC308",
                        message=f"docs/observability.md default-ruleset "
                                f"table lists `{name}` but "
                                "health.DEFAULT_RULES has no such rule",
                        path="docs/observability.md", line=1, scope="",
                        snippet=name))
        # [alerts] config keys <-> health.CONFIG_KEYS, both directions:
        # a declared key the engine never reads is dead config; an
        # accepted key config doesn't declare is unreachable
        schema = _module_tuple(hmod, "CONFIG_KEYS")
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if schema is not None and cfg_mod is not None:
            declared = {k for sec, k in _default_config_keys(cfg_mod)
                        if sec == "alerts"}
            if declared:
                for k in sorted(declared - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC308",
                        f"config key `[alerts] {k}` is declared but "
                        "health.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - declared):
                    out.append(hmod.finding(
                        "SC308",
                        f"health.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[alerts] {k}`", hmod.tree))
        return out

    # -- SC309 -----------------------------------------------------------

    _EFF_DOC_BLOCK_RE = re.compile(
        r"<!--\s*efficiency-series:begin\s*-->(.*?)"
        r"<!--\s*efficiency-series:end\s*-->", re.S)

    @staticmethod
    def _tpu_kernel_classes(mod: ModuleInfo
                            ) -> List[Tuple[str, bool, ast.AST]]:
        """(class name, has_cost, node) for every class registered as a
        TPU device op via a @register_op(device=DeviceType.TPU, ...)
        decorator."""
        out: List[Tuple[str, bool, ast.AST]] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            is_tpu = False
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                if (dotted_name(dec.func) or "").split(".")[-1] \
                        != "register_op":
                    continue
                for kw in dec.keywords:
                    if kw.arg == "device" and (
                            dotted_name(kw.value) or "").endswith("TPU"):
                        is_tpu = True
            if is_tpu:
                has_cost = any(
                    isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and b.name == "cost" for b in node.body)
                out.append((node.name, has_cost, node))
        return out

    def _cost_model(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        # direction 1: every TPU device kernel in kernels/ declares its
        # analytical cost() hook — the roofline join otherwise degrades
        # to derived defaults silently, and a new stdlib op would ship
        # without an efficiency story
        for mod in project.modules:
            if "kernels/" not in mod.relpath:
                continue
            for name, has_cost, node in self._tpu_kernel_classes(mod):
                if not has_cost:
                    out.append(mod.finding(
                        "SC309",
                        f"TPU device kernel `{name}` declares no "
                        "cost() descriptor hook — roofline attribution "
                        "(util/coststats.py) falls back to derived "
                        "defaults; declare FLOPs/bytes as f(shape) or "
                        "justify the fallback", node))
        # directions 2+3: EFFICIENCY_SERIES <-> the series coststats
        # actually registers <-> the marker-delimited efficiency table
        # in docs/observability.md, both ways each
        cmod = project.module("util/coststats.py")
        if cmod is None:
            return out
        declared = _module_tuple(cmod, "EFFICIENCY_SERIES")
        if declared is None:
            return out
        declared_set = set(declared)
        registered = {r.name for r in _metric_registrations(cmod)
                      if r.name}
        for name in sorted(registered - declared_set):
            out.append(cmod.finding(
                "SC309",
                f"series `{name}` is registered in coststats but "
                "missing from EFFICIENCY_SERIES — the SC309 catalog "
                "contract cannot see it", cmod.tree))
        for name in sorted(declared_set - registered):
            out.append(cmod.finding(
                "SC309",
                f"EFFICIENCY_SERIES names `{name}` but coststats "
                "registers no such series", cmod.tree))
        doc = _read_doc(project, "observability.md")
        if not doc:
            return out
        block = self._EFF_DOC_BLOCK_RE.search(doc)
        if block is None:
            out.append(cmod.finding(
                "SC309",
                "coststats declares EFFICIENCY_SERIES but docs/"
                "observability.md has no efficiency-series marker "
                "table (<!-- efficiency-series:begin/end -->)",
                cmod.tree))
            return out
        doc_names = {n for n in _SERIES_RE.findall(block.group(1))}
        base_doc = set()
        for n in doc_names:
            for suf in _EXPOSITION_SUFFIXES:
                if n.endswith(suf) and n[:-len(suf)] in doc_names:
                    break
            else:
                base_doc.add(n)
        for name in sorted(declared_set - base_doc):
            out.append(cmod.finding(
                "SC309",
                f"efficiency series `{name}` is missing from the "
                "docs/observability.md efficiency-series table",
                cmod.tree))
        for name in sorted(base_doc - declared_set):
            out.append(Finding(
                code="SC309",
                message=f"docs/observability.md efficiency-series "
                        f"table lists `{name}` but coststats' "
                        "EFFICIENCY_SERIES has no such series",
                path="docs/observability.md", line=1, scope="",
                snippet=name))
        return out

    # -- SC310 -----------------------------------------------------------

    _FC_DOC_BLOCK_RE = re.compile(
        r"<!--\s*framecache-series:begin\s*-->(.*?)"
        r"<!--\s*framecache-series:end\s*-->", re.S)

    def _frame_cache(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        fmod = project.module("engine/framecache.py")
        if fmod is None:
            return out
        declared = _module_tuple(fmod, "FRAMECACHE_SERIES")
        if declared is not None:
            declared_set = set(declared)
            registered = {r.name for r in _metric_registrations(fmod)
                          if r.name}
            for name in sorted(registered - declared_set):
                out.append(fmod.finding(
                    "SC310",
                    f"series `{name}` is registered in framecache but "
                    "missing from FRAMECACHE_SERIES — the SC310 catalog "
                    "contract cannot see it", fmod.tree))
            for name in sorted(declared_set - registered):
                out.append(fmod.finding(
                    "SC310",
                    f"FRAMECACHE_SERIES names `{name}` but framecache "
                    "registers no such series", fmod.tree))
            doc = _read_doc(project, "observability.md")
            if doc:
                block = self._FC_DOC_BLOCK_RE.search(doc)
                if block is None:
                    out.append(fmod.finding(
                        "SC310",
                        "framecache declares FRAMECACHE_SERIES but "
                        "docs/observability.md has no framecache-series "
                        "marker table (<!-- framecache-series:begin/end "
                        "-->)", fmod.tree))
                else:
                    doc_names = {n for n in
                                 _SERIES_RE.findall(block.group(1))}
                    base_doc = set()
                    for n in doc_names:
                        for suf in _EXPOSITION_SUFFIXES:
                            if n.endswith(suf) \
                                    and n[:-len(suf)] in doc_names:
                                break
                        else:
                            base_doc.add(n)
                    for name in sorted(declared_set - base_doc):
                        out.append(fmod.finding(
                            "SC310",
                            f"frame-cache series `{name}` is missing "
                            "from the docs/observability.md "
                            "framecache-series table", fmod.tree))
                    for name in sorted(base_doc - declared_set):
                        out.append(Finding(
                            code="SC310",
                            message=f"docs/observability.md "
                                    f"framecache-series table lists "
                                    f"`{name}` but framecache's "
                                    "FRAMECACHE_SERIES has no such "
                                    "series",
                            path="docs/observability.md", line=1,
                            scope="", snippet=name))
        # [perf] frame_cache_* config keys <-> framecache.CONFIG_KEYS,
        # both directions (the SC308 [alerts] pattern): a declared key
        # the cache never reads is dead config; an accepted key config
        # doesn't declare is unreachable
        schema = _module_tuple(fmod, "CONFIG_KEYS")
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if schema is not None and cfg_mod is not None:
            perf_keys = {k for sec, k in _default_config_keys(cfg_mod)
                         if sec == "perf"
                         and k.startswith("frame_cache")}
            if perf_keys or schema:
                for k in sorted(perf_keys - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC310",
                        f"config key `[perf] {k}` is declared but "
                        "framecache.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - perf_keys):
                    out.append(fmod.finding(
                        "SC310",
                        f"framecache.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[perf] {k}`", fmod.tree))
        return out

    # -- SC311 -----------------------------------------------------------

    _PB_DOC_BLOCK_RE = re.compile(
        r"<!--\s*remediation-playbooks:begin\s*-->(.*?)"
        r"<!--\s*remediation-playbooks:end\s*-->", re.S)
    # matrix rows lead `| `playbook` | `alert` | ...`
    _PB_DOC_ROW_RE = re.compile(
        r"^\|\s*`([a-z0-9_]+)`\s*\|\s*`([a-z0-9_]+)`", re.M)

    @staticmethod
    def _default_playbooks(mod: ModuleInfo
                           ) -> Optional[List[Tuple[str, str, ast.AST]]]:
        """(name, alert, node) per element of the module-level
        DEFAULT_PLAYBOOKS tuple — the literal `name=`/`alert=` kwargs
        of each playbook constructor call."""
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "DEFAULT_PLAYBOOKS" \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)):
                out: List[Tuple[str, str, ast.AST]] = []
                for el in stmt.value.elts:
                    if not isinstance(el, ast.Call):
                        continue
                    name = alert = None
                    for kw in el.keywords:
                        if kw.arg == "name":
                            name = _const_str(kw.value)
                        elif kw.arg == "alert":
                            alert = _const_str(kw.value)
                    if name is not None and alert is not None:
                        out.append((name, alert, el))
                return out
        return None

    def _remediation(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        cmod = project.module("engine/controller.py")
        if cmod is None:
            return out
        playbooks = self._default_playbooks(cmod)
        if playbooks:
            # direction 1: every playbook binds a REAL alert — an
            # action wired to a rule name the health engine never
            # evaluates can never fire
            hmod = project.module("util/health.py")
            rule_names = {n for n, _node in
                          (self._default_rule_names(hmod) or ())} \
                if hmod is not None else None
            if rule_names is not None:
                for name, alert, node in playbooks:
                    if alert not in rule_names:
                        out.append(cmod.finding(
                            "SC311",
                            f"playbook `{name}` binds alert `{alert}` "
                            "but health.DEFAULT_RULES has no such rule "
                            "— the playbook can never fire", node))
            # directions 2+3: playbook names + alert bindings <-> the
            # docs/robustness.md marker matrix, both ways
            doc = _read_doc(project, "robustness.md")
            block = self._PB_DOC_BLOCK_RE.search(doc) if doc else None
            if doc and block is None:
                out.append(cmod.finding(
                    "SC311",
                    "controller declares DEFAULT_PLAYBOOKS but docs/"
                    "robustness.md has no remediation-playbooks marker "
                    "table (<!-- remediation-playbooks:begin/end -->) — "
                    "operators cannot see what auto-remediates",
                    cmod.tree))
            elif block is not None:
                doc_rows = dict(
                    self._PB_DOC_ROW_RE.findall(block.group(1)))
                by_name = {n: (a, node) for n, a, node in playbooks}
                for name, (alert, node) in sorted(by_name.items()):
                    if name not in doc_rows:
                        out.append(cmod.finding(
                            "SC311",
                            f"playbook `{name}` is missing from the "
                            "docs/robustness.md remediation-playbooks "
                            "matrix", node))
                    elif doc_rows[name] != alert:
                        out.append(cmod.finding(
                            "SC311",
                            f"playbook `{name}` binds alert `{alert}` "
                            f"but the docs matrix row says "
                            f"`{doc_rows[name]}`", node))
                for name in sorted(set(doc_rows) - set(by_name)):
                    out.append(Finding(
                        code="SC311",
                        message=f"docs/robustness.md "
                                f"remediation-playbooks matrix lists "
                                f"`{name}` but controller."
                                "DEFAULT_PLAYBOOKS has no such "
                                "playbook",
                        path="docs/robustness.md", line=1, scope="",
                        snippet=name))
        # [remediation] config keys <-> controller.CONFIG_KEYS, both
        # directions (the SC308/[alerts] pattern)
        schema = _module_tuple(cmod, "CONFIG_KEYS")
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if schema is not None and cfg_mod is not None:
            declared = {k for sec, k in _default_config_keys(cfg_mod)
                        if sec == "remediation"}
            if declared:
                for k in sorted(declared - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC311",
                        f"config key `[remediation] {k}` is declared "
                        "but controller.CONFIG_KEYS does not accept "
                        "it", cfg_mod.tree))
                for k in sorted(set(schema) - declared):
                    out.append(cmod.finding(
                        "SC311",
                        f"controller.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[remediation] {k}`", cmod.tree))
        return out

    # -- SC312 -----------------------------------------------------------

    @staticmethod
    def _contract_idempotency(mod: ModuleInfo) -> Optional[Dict[str, object]]:
        """{method: idempotent-const-or-None} from the module-level
        RPC_CONTRACTS dict literal (None when the flag is not a bool
        constant — SC307 already flags incomplete entries)."""
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "RPC_CONTRACTS" \
                    and isinstance(stmt.value, ast.Dict):
                out: Dict[str, object] = {}
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    ks = _const_str(k)
                    if ks is None:
                        continue
                    idem = None
                    if isinstance(v, ast.Dict):
                        for vk, vv in zip(v.keys, v.values):
                            if _const_str(vk) == "idempotent" \
                                    and isinstance(vv, ast.Constant) \
                                    and isinstance(vv.value, bool):
                                idem = vv.value
                    out[ks] = idem
                return out
        return None

    @staticmethod
    def _master_registrations(mod: ModuleInfo
                              ) -> Dict[str, Tuple[bool, ast.AST]]:
        """{method: (fence_wrapped, key_node)} from the RpcServer
        registration whose service argument resolves through
        MASTER_SERVICE — the fence only guards the master's control
        plane (worker-service handlers are all idempotent reads)."""
        out: Dict[str, Tuple[bool, ast.AST]] = {}
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and (dotted_name(node.func) or "").split(".")[-1]
                    == "RpcServer" and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Dict)):
                continue
            svc = dotted_name(node.args[0]) or ""
            if not svc.split(".")[-1] == "MASTER_SERVICE":
                continue
            for k, v in zip(node.args[1].keys, node.args[1].values):
                name = _const_str(k)
                if name is None:
                    continue
                wrapped = isinstance(v, ast.Call) and (
                    dotted_name(v.func) or "").split(".")[-1] \
                    == "_fenced"
                out[name] = (wrapped, k)
        return out

    def _fence_routing(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        cmod: Optional[ModuleInfo] = None
        contracts: Optional[Dict[str, object]] = None
        for mod in project.modules:
            got = self._contract_idempotency(mod)
            if got is not None:
                cmod, contracts = mod, got
                break
        if cmod is not None and contracts is not None:
            registered = self._master_registrations(cmod)
            if registered:
                # direction 1: every mutating (idempotent=False)
                # contract routes its master handler through the fence
                for name, idem in sorted(contracts.items()):
                    if idem is not False or name not in registered:
                        continue
                    wrapped, node = registered[name]
                    if not wrapped:
                        out.append(cmod.finding(
                            "SC312",
                            f"RPC `{name}` is classified "
                            "idempotent=False but its master handler "
                            "is registered without the generation-"
                            "fence wrapper (`self._fenced(...)`) — a "
                            "superseded (stale) master would keep "
                            "accepting this mutation", node))
                # direction 2: every fence-wrapped registration is
                # classified non-idempotent — fencing a read means the
                # table and the code disagree about what mutates
                for name, (wrapped, node) in sorted(registered.items()):
                    if wrapped and contracts.get(name) is not False:
                        out.append(cmod.finding(
                            "SC312",
                            f"master handler `{name}` is wrapped in "
                            "the generation fence but RPC_CONTRACTS "
                            "does not classify it idempotent=False — "
                            "the table and the fence routing disagree "
                            "about whether it mutates", node))
        # [robustness] journal_* config keys <-> journal.CONFIG_KEYS,
        # both directions (the SC308/SC310/SC311 pattern)
        jmod = project.module("engine/journal.py")
        schema = _module_tuple(jmod, "CONFIG_KEYS") \
            if jmod is not None else None
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if jmod is not None and schema is not None \
                and cfg_mod is not None:
            declared = {k for sec, k in _default_config_keys(cfg_mod)
                        if sec == "robustness"
                        and k.startswith("journal")}
            if declared or schema:
                for k in sorted(declared - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC312",
                        f"config key `[robustness] {k}` is declared "
                        "but journal.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - declared):
                    out.append(jmod.finding(
                        "SC312",
                        f"journal.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[robustness] {k}`", jmod.tree))
        return out

    # -- SC313 -----------------------------------------------------------

    _GANG_DOC_KEY_RE = re.compile(r"`\[gang\]\s+([a-z0-9_]+)`")

    def _gang_contract(self, project: Project) -> List[Finding]:
        """Gang contract lints: the Gang* RPC surface's fencing shape
        (specializing SC312 — a gang RPC must be BOTH classified
        non-idempotent and fence-wrapped, whichever side drifted), and
        the three-way [gang] config pairing (default_config ↔
        gang.CONFIG_KEYS ↔ docs/guide.md rows)."""
        out: List[Finding] = []
        cmod: Optional[ModuleInfo] = None
        contracts: Optional[Dict[str, object]] = None
        for mod in project.modules:
            got = self._contract_idempotency(mod)
            if got is not None:
                cmod, contracts = mod, got
                break
        if cmod is not None and contracts is not None:
            registered = self._master_registrations(cmod)
            gang_entries = sorted(n for n in contracts
                                  if n.startswith("Gang"))
            for name in gang_entries:
                if contracts.get(name) is not False:
                    out.append(cmod.finding(
                        "SC313",
                        f"gang RPC `{name}` is not classified "
                        "idempotent=False in RPC_CONTRACTS — gang "
                        "RPCs mutate scheduling state behind the "
                        "(gang_id, epoch) fence and must never ride "
                        "the blind-retry path", cmod.tree))
                if registered and name not in registered:
                    out.append(cmod.finding(
                        "SC313",
                        f"gang RPC `{name}` has an RPC_CONTRACTS "
                        "entry but no MASTER_SERVICE handler "
                        "registration", cmod.tree))
                elif registered and not registered[name][0]:
                    out.append(cmod.finding(
                        "SC313",
                        f"gang RPC `{name}`'s master handler is "
                        "registered without the generation-fence "
                        "wrapper (`self._fenced(...)`) — a superseded "
                        "master could keep accepting gang mutations",
                        registered[name][1]))
            if registered:
                for name, (_wrapped, node) in sorted(
                        registered.items()):
                    if name.startswith("Gang") \
                            and name not in contracts:
                        out.append(cmod.finding(
                            "SC313",
                            f"master registers gang handler `{name}` "
                            "with no RPC_CONTRACTS entry — the gang "
                            "surface must be classified", node))
        # [gang] config keys <-> gang.CONFIG_KEYS <-> docs/guide.md
        # rows, all pairings both directions (the SC312 journal
        # pattern plus the doc leg)
        gmod = project.module("engine/gang.py")
        schema = _module_tuple(gmod, "CONFIG_KEYS") \
            if gmod is not None else None
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if gmod is not None and schema is not None \
                and cfg_mod is not None:
            declared = {k for sec, k in _default_config_keys(cfg_mod)
                        if sec == "gang"}
            if declared or schema:
                for k in sorted(declared - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC313",
                        f"config key `[gang] {k}` is declared but "
                        "gang.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - declared):
                    out.append(gmod.finding(
                        "SC313",
                        f"gang.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[gang] {k}`", gmod.tree))
                doc = _read_doc(project, "guide.md")
                if doc:
                    doc_keys = set(self._GANG_DOC_KEY_RE.findall(doc))
                    for k in sorted(set(schema) - doc_keys):
                        out.append(gmod.finding(
                            "SC313",
                            f"gang.CONFIG_KEYS accepts `{k}` but "
                            "docs/guide.md has no `[gang] "
                            f"{k}` row", gmod.tree))
                    for k in sorted(doc_keys - set(schema)):
                        out.append(Finding(
                            code="SC313",
                            message=f"docs/guide.md documents "
                                    f"`[gang] {k}` but "
                                    "gang.CONFIG_KEYS accepts no such "
                                    "key",
                            path="docs/guide.md", line=1, scope="",
                            snippet=k))
        return out

    # -- SC314 -----------------------------------------------------------

    _CS_DOC_BLOCK_RE = re.compile(
        r"<!--\s*clocksync-series:begin\s*-->(.*?)"
        r"<!--\s*clocksync-series:end\s*-->", re.S)
    _PHASE_DOC_BLOCK_RE = re.compile(
        r"<!--\s*gang-phase-taxonomy:begin\s*-->(.*?)"
        r"<!--\s*gang-phase-taxonomy:end\s*-->", re.S)
    _GANG_SPAN_RE = re.compile(r"`(gang\.[a-z0-9_.]+)`")

    @staticmethod
    def _doc_base_series(block_text: str) -> Set[str]:
        """Series names in a doc block, exposition suffixes folded
        into their base series (the SC309/SC310 convention)."""
        doc_names = set(_SERIES_RE.findall(block_text))
        base = set()
        for n in doc_names:
            for suf in _EXPOSITION_SUFFIXES:
                if n.endswith(suf) and n[:-len(suf)] in doc_names:
                    break
            else:
                base.add(n)
        return base

    @staticmethod
    def _gang_span_names(mod: ModuleInfo) -> Set[str]:
        """Every `gang.*` string literal handed to an open_span call —
        the code-side phase taxonomy."""
        names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "open_span"):
                continue
            for arg in node.args:
                s = _const_str(arg)
                if s is not None and s.startswith("gang."):
                    names.add(s)
        return names

    def _clocksync_contract(self, project: Project) -> List[Finding]:
        """Cross-host time lints: the clock-sync + gang-phase metric
        surface (module tuples ↔ registrations ↔ the clocksync-series
        doc table), the gang phase-span taxonomy (open_span literals
        ↔ the gang-phase-taxonomy doc table), and the `[trace]` clock
        keys (default_config ↔ clocksync.CONFIG_KEYS).  Merged
        timelines are only trustworthy if the reader can look every
        series and span name up — an undocumented phase is a blind
        spot in exactly the trace meant to explain stragglers."""
        out: List[Finding] = []
        csmod = project.module("util/clocksync.py")
        if csmod is None:
            return out
        gmod = project.module("engine/gang.py")
        doc = _read_doc(project, "observability.md")

        declared_union: Set[str] = set()
        have_tuple = False
        # per-module: the declared tuple must match what the module
        # registers.  clocksync registers nothing but clock series, so
        # the pairing is exact; gang.py also owns lifecycle counters,
        # so the reverse leg only claims phase/skew-named series
        cs_series = _module_tuple(csmod, "CLOCKSYNC_SERIES")
        if cs_series is not None:
            have_tuple = True
            declared_union |= set(cs_series)
            registered = {r.name for r in _metric_registrations(csmod)
                          if r.name}
            for name in sorted(registered - set(cs_series)):
                out.append(csmod.finding(
                    "SC314",
                    f"series `{name}` is registered in clocksync but "
                    "missing from CLOCKSYNC_SERIES — the SC314 catalog "
                    "contract cannot see it", csmod.tree))
            for name in sorted(set(cs_series) - registered):
                out.append(csmod.finding(
                    "SC314",
                    f"CLOCKSYNC_SERIES names `{name}` but clocksync "
                    "registers no such series", csmod.tree))
        gp_series = _module_tuple(gmod, "GANG_PHASE_SERIES") \
            if gmod is not None else None
        if gp_series is not None and gmod is not None:
            have_tuple = True
            declared_union |= set(gp_series)
            registered = {r.name for r in _metric_registrations(gmod)
                          if r.name}
            phase_named = {n for n in registered
                           if "_phase_" in n or "_skew_" in n}
            for name in sorted(phase_named - set(gp_series)):
                out.append(gmod.finding(
                    "SC314",
                    f"series `{name}` is registered in gang but "
                    "missing from GANG_PHASE_SERIES — the SC314 "
                    "catalog contract cannot see it", gmod.tree))
            for name in sorted(set(gp_series) - registered):
                out.append(gmod.finding(
                    "SC314",
                    f"GANG_PHASE_SERIES names `{name}` but gang "
                    "registers no such series", gmod.tree))
        # union <-> the clocksync-series doc table, both directions
        if have_tuple and doc:
            block = self._CS_DOC_BLOCK_RE.search(doc)
            if block is None:
                out.append(csmod.finding(
                    "SC314",
                    "clocksync declares CLOCKSYNC_SERIES but "
                    "docs/observability.md has no clocksync-series "
                    "marker table (<!-- clocksync-series:begin/end "
                    "-->)", csmod.tree))
            else:
                base_doc = self._doc_base_series(block.group(1))
                for name in sorted(declared_union - base_doc):
                    out.append(csmod.finding(
                        "SC314",
                        f"cross-host time series `{name}` is missing "
                        "from the docs/observability.md "
                        "clocksync-series table", csmod.tree))
                for name in sorted(base_doc - declared_union):
                    out.append(Finding(
                        code="SC314",
                        message=f"docs/observability.md "
                                f"clocksync-series table lists "
                                f"`{name}` but neither "
                                "CLOCKSYNC_SERIES nor "
                                "GANG_PHASE_SERIES has such a series",
                        path="docs/observability.md", line=1,
                        scope="", snippet=name))
        # gang.* phase spans <-> the gang-phase-taxonomy doc table,
        # both directions
        span_names = self._gang_span_names(gmod) \
            if gmod is not None else set()
        if span_names and doc and gmod is not None:
            block = self._PHASE_DOC_BLOCK_RE.search(doc)
            if block is None:
                out.append(gmod.finding(
                    "SC314",
                    "gang opens phase spans but docs/observability.md "
                    "has no gang-phase-taxonomy marker table (<!-- "
                    "gang-phase-taxonomy:begin/end -->)", gmod.tree))
            else:
                doc_spans = set(
                    self._GANG_SPAN_RE.findall(block.group(1)))
                for name in sorted(span_names - doc_spans):
                    out.append(gmod.finding(
                        "SC314",
                        f"gang opens span `{name}` but the "
                        "docs/observability.md gang-phase-taxonomy "
                        "table has no row for it — the merged "
                        "timeline would show an unexplained phase",
                        gmod.tree))
                for name in sorted(doc_spans - span_names):
                    out.append(Finding(
                        code="SC314",
                        message=f"docs/observability.md "
                                f"gang-phase-taxonomy table documents "
                                f"span `{name}` but gang opens no "
                                "such span",
                        path="docs/observability.md", line=1,
                        scope="", snippet=name))
        # [trace] clock keys <-> clocksync.CONFIG_KEYS, both
        # directions.  `enabled` is the tracing core's own switch and
        # is excluded; everything else under [trace] belongs to the
        # clock-sync layer and must be declared by it
        schema = _module_tuple(csmod, "CONFIG_KEYS")
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if schema is not None and cfg_mod is not None:
            trace_keys = {k for sec, k in _default_config_keys(cfg_mod)
                          if sec == "trace" and k != "enabled"}
            if trace_keys or schema:
                for k in sorted(trace_keys - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC314",
                        f"config key `[trace] {k}` is declared but "
                        "clocksync.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - trace_keys):
                    out.append(csmod.finding(
                        "SC314",
                        f"clocksync.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[trace] {k}`", csmod.tree))
        return out

    # -- SC315 -----------------------------------------------------------

    _SHARD_DOC_BLOCK_RE = re.compile(
        r"<!--\s*gang-shard-series:begin\s*-->(.*?)"
        r"<!--\s*gang-shard-series:end\s*-->", re.S)
    # the [gang] keys that gate the sharded data plane: mode switch +
    # halo exchange.  They must exist wherever the plane's series do —
    # a data plane without its kill switches strands an operator mid-
    # incident, and gates with no plane are stale doc surface
    _SHARD_GATE_KEYS = ("sharded", "halo_exchange")

    def _gang_shard_contract(self, project: Project) -> List[Finding]:
        """Sharded gang data-plane lints: GANG_SHARD_SERIES ↔ the
        `_shard_`-named series engine/gang.py registers ↔ the
        gang-shard-series marker table in docs/observability.md (all
        pairings, both directions), plus the travel-together rule for
        the `[gang] sharded`/`halo_exchange` gates (gang.CONFIG_KEYS
        and config.default_config() must both declare them exactly
        when the data plane exists)."""
        out: List[Finding] = []
        gmod = project.module("engine/gang.py")
        if gmod is None:
            return out
        series = _module_tuple(gmod, "GANG_SHARD_SERIES")
        registered = {r.name for r in _metric_registrations(gmod)
                      if r.name}
        shard_named = {n for n in registered if "_shard_" in n}
        schema = _module_tuple(gmod, "CONFIG_KEYS") or ()
        if series is None:
            if shard_named:
                out.append(gmod.finding(
                    "SC315",
                    "gang registers shard series ("
                    + ", ".join(f"`{n}`" for n in sorted(shard_named))
                    + ") but declares no GANG_SHARD_SERIES tuple — "
                    "the SC315 catalog contract cannot see them",
                    gmod.tree))
            else:
                for k in self._SHARD_GATE_KEYS:
                    if k in schema:
                        out.append(gmod.finding(
                            "SC315",
                            f"gang.CONFIG_KEYS accepts `{k}` but the "
                            "module declares no GANG_SHARD_SERIES "
                            "data plane — a sharding gate with "
                            "nothing to gate", gmod.tree))
            return out
        for name in sorted(shard_named - set(series)):
            out.append(gmod.finding(
                "SC315",
                f"series `{name}` is registered in gang but missing "
                "from GANG_SHARD_SERIES — the SC315 catalog contract "
                "cannot see it", gmod.tree))
        for name in sorted(set(series) - registered):
            out.append(gmod.finding(
                "SC315",
                f"GANG_SHARD_SERIES names `{name}` but gang "
                "registers no such series", gmod.tree))
        doc = _read_doc(project, "observability.md")
        if doc:
            block = self._SHARD_DOC_BLOCK_RE.search(doc)
            if block is None:
                out.append(gmod.finding(
                    "SC315",
                    "gang declares GANG_SHARD_SERIES but "
                    "docs/observability.md has no gang-shard-series "
                    "marker table (<!-- gang-shard-series:begin/end "
                    "-->)", gmod.tree))
            else:
                base_doc = self._doc_base_series(block.group(1))
                for name in sorted(set(series) - base_doc):
                    out.append(gmod.finding(
                        "SC315",
                        f"sharded gang series `{name}` is missing "
                        "from the docs/observability.md "
                        "gang-shard-series table", gmod.tree))
                for name in sorted(base_doc - set(series)):
                    out.append(Finding(
                        code="SC315",
                        message="docs/observability.md "
                                "gang-shard-series table lists "
                                f"`{name}` but GANG_SHARD_SERIES has "
                                "no such series",
                        path="docs/observability.md", line=1,
                        scope="", snippet=name))
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        gang_cfg = {k for sec, k in _default_config_keys(cfg_mod)
                    if sec == "gang"} if cfg_mod is not None else None
        for k in self._SHARD_GATE_KEYS:
            if k not in schema:
                out.append(gmod.finding(
                    "SC315",
                    "gang declares GANG_SHARD_SERIES but "
                    f"gang.CONFIG_KEYS has no `{k}` gate — the "
                    "sharded data plane ships without its kill "
                    "switch", gmod.tree))
            if gang_cfg is not None and k not in gang_cfg:
                out.append(cfg_mod.finding(
                    "SC315",
                    "gang declares GANG_SHARD_SERIES but "
                    f"config.default_config() declares no `[gang] "
                    f"{k}` — the sharded data plane ships without "
                    "its declared default", cfg_mod.tree))
        return out

    # -- SC316 -----------------------------------------------------------

    _SHARDMAP_DOC_BLOCK_RE = re.compile(
        r"<!--\s*shard-series:begin\s*-->(.*?)"
        r"<!--\s*shard-series:end\s*-->", re.S)

    def _shard_contract(self, project: Project) -> List[Finding]:
        """Sharded control-plane lints: shardmap.SHARD_SERIES ↔ the
        series engine/shardmap.py registers ↔ the shard-series marker
        table in docs/observability.md (all pairings, both
        directions); `[control]` keys in config.default_config() ↔
        shardmap.CONFIG_KEYS (both directions); and the shard-routing
        leg extending SC312 — every service.SHARD_ROUTED_RPCS method
        must be classified idempotent=False AND fence-wrapped, and
        every idempotent=False contract must be shard-routed, so a
        mutating RPC can never land on a master that does not own
        the bulk it mutates."""
        out: List[Finding] = []
        shmod = project.module("engine/shardmap.py")
        if shmod is None:
            return out
        series = _module_tuple(shmod, "SHARD_SERIES")
        registered = {r.name for r in _metric_registrations(shmod)
                      if r.name}
        if series is None:
            if registered:
                out.append(shmod.finding(
                    "SC316",
                    "shardmap registers series ("
                    + ", ".join(f"`{n}`" for n in sorted(registered))
                    + ") but declares no SHARD_SERIES tuple — the "
                    "SC316 catalog contract cannot see them",
                    shmod.tree))
        else:
            for name in sorted(registered - set(series)):
                out.append(shmod.finding(
                    "SC316",
                    f"series `{name}` is registered in shardmap but "
                    "missing from SHARD_SERIES — the SC316 catalog "
                    "contract cannot see it", shmod.tree))
            for name in sorted(set(series) - registered):
                out.append(shmod.finding(
                    "SC316",
                    f"SHARD_SERIES names `{name}` but shardmap "
                    "registers no such series", shmod.tree))
            doc = _read_doc(project, "observability.md")
            if doc:
                block = self._SHARDMAP_DOC_BLOCK_RE.search(doc)
                if block is None:
                    out.append(shmod.finding(
                        "SC316",
                        "shardmap declares SHARD_SERIES but "
                        "docs/observability.md has no shard-series "
                        "marker table (<!-- shard-series:begin/end "
                        "-->)", shmod.tree))
                else:
                    base_doc = self._doc_base_series(block.group(1))
                    for name in sorted(set(series) - base_doc):
                        out.append(shmod.finding(
                            "SC316",
                            f"control-plane shard series `{name}` is "
                            "missing from the docs/observability.md "
                            "shard-series table", shmod.tree))
                    for name in sorted(base_doc - set(series)):
                        out.append(Finding(
                            code="SC316",
                            message="docs/observability.md "
                                    "shard-series table lists "
                                    f"`{name}` but SHARD_SERIES has "
                                    "no such series",
                            path="docs/observability.md", line=1,
                            scope="", snippet=name))
        # [control] keys <-> shardmap.CONFIG_KEYS, both directions
        schema = _module_tuple(shmod, "CONFIG_KEYS")
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if schema is not None and cfg_mod is not None:
            control_keys = {k for sec, k in
                            _default_config_keys(cfg_mod)
                            if sec == "control"}
            if control_keys or schema:
                for k in sorted(control_keys - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC316",
                        f"config key `[control] {k}` is declared but "
                        "shardmap.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - control_keys):
                    out.append(shmod.finding(
                        "SC316",
                        f"shardmap.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[control] {k}`", shmod.tree))
        # shard-routing leg (extends SC312): SHARD_ROUTED_RPCS <->
        # the idempotent=False, fence-wrapped master surface.  A
        # mutating RPC must follow the bulk to its owning shard AND
        # stay behind the generation fence there — routing without
        # fencing (or vice versa) reopens the stale-master window
        # sharding was meant to close.
        smod = project.module("engine/service.py")
        routed = _module_tuple(smod, "SHARD_ROUTED_RPCS") \
            if smod is not None else None
        if smod is None or routed is None:
            return out
        contracts = self._contract_idempotency(smod)
        registered_m = self._master_registrations(smod)
        if contracts is None or not registered_m:
            return out
        for name in routed:
            if name not in contracts:
                out.append(smod.finding(
                    "SC316",
                    f"SHARD_ROUTED_RPCS routes `{name}` but "
                    "RPC_CONTRACTS has no such entry — an "
                    "unclassified method cannot be routed safely",
                    smod.tree))
                continue
            if contracts.get(name) is not False:
                out.append(smod.finding(
                    "SC316",
                    f"SHARD_ROUTED_RPCS routes `{name}` but "
                    "RPC_CONTRACTS does not classify it "
                    "idempotent=False — only mutating RPCs follow "
                    "the bulk to its owning shard", smod.tree))
            reg = registered_m.get(name)
            if reg is None:
                out.append(smod.finding(
                    "SC316",
                    f"SHARD_ROUTED_RPCS routes `{name}` but the "
                    "master service registers no such handler",
                    smod.tree))
            elif not reg[0]:
                out.append(smod.finding(
                    "SC316",
                    f"shard-routed RPC `{name}` is registered "
                    "without the generation-fence wrapper "
                    "(`self._fenced(...)`) — a superseded shard "
                    "master would keep accepting this mutation",
                    reg[1]))
        for name, idem in sorted(contracts.items()):
            if idem is False and name not in routed:
                out.append(smod.finding(
                    "SC316",
                    f"RPC `{name}` is classified idempotent=False "
                    "but is missing from SHARD_ROUTED_RPCS — a "
                    "mutating RPC pinned to the dial-time shard "
                    "would bypass bulk ownership", smod.tree))
        return out

    # -- SC317 -----------------------------------------------------------

    _FUSION_DOC_BLOCK_RE = re.compile(
        r"<!--\s*fusion-series:begin\s*-->(.*?)"
        r"<!--\s*fusion-series:end\s*-->", re.S)

    def _fusion_contract(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        fmod = project.module("graph/fusion.py")
        if fmod is None:
            return out
        declared = _module_tuple(fmod, "FUSION_SERIES")
        if declared is not None:
            declared_set = set(declared)
            registered = {r.name for r in _metric_registrations(fmod)
                          if r.name}
            for name in sorted(registered - declared_set):
                out.append(fmod.finding(
                    "SC317",
                    f"series `{name}` is registered in fusion but "
                    "missing from FUSION_SERIES — the SC317 catalog "
                    "contract cannot see it", fmod.tree))
            for name in sorted(declared_set - registered):
                out.append(fmod.finding(
                    "SC317",
                    f"FUSION_SERIES names `{name}` but fusion "
                    "registers no such series", fmod.tree))
            doc = _read_doc(project, "observability.md")
            if doc:
                block = self._FUSION_DOC_BLOCK_RE.search(doc)
                if block is None:
                    out.append(fmod.finding(
                        "SC317",
                        "fusion declares FUSION_SERIES but "
                        "docs/observability.md has no fusion-series "
                        "marker table (<!-- fusion-series:begin/end "
                        "-->)", fmod.tree))
                else:
                    doc_names = {n for n in
                                 _SERIES_RE.findall(block.group(1))}
                    base_doc = set()
                    for n in doc_names:
                        for suf in _EXPOSITION_SUFFIXES:
                            if n.endswith(suf) \
                                    and n[:-len(suf)] in doc_names:
                                break
                        else:
                            base_doc.add(n)
                    for name in sorted(declared_set - base_doc):
                        out.append(fmod.finding(
                            "SC317",
                            f"fusion series `{name}` is missing from "
                            "the docs/observability.md fusion-series "
                            "table", fmod.tree))
                    for name in sorted(base_doc - declared_set):
                        out.append(Finding(
                            code="SC317",
                            message=f"docs/observability.md "
                                    f"fusion-series table lists "
                                    f"`{name}` but fusion's "
                                    "FUSION_SERIES has no such series",
                            path="docs/observability.md", line=1,
                            scope="", snippet=name))
        # [perf] fusion_* config keys <-> fusion.CONFIG_KEYS, both
        # directions (the SC310 frame_cache_* pattern)
        schema = _module_tuple(fmod, "CONFIG_KEYS")
        cfg_mod = None
        for m in project.modules:
            if m.relpath.endswith("config.py") \
                    and _default_config_keys(m):
                cfg_mod = m
                break
        if schema is not None and cfg_mod is not None:
            perf_keys = {k for sec, k in _default_config_keys(cfg_mod)
                         if sec == "perf" and k.startswith("fusion")}
            if perf_keys or schema:
                for k in sorted(perf_keys - set(schema)):
                    out.append(cfg_mod.finding(
                        "SC317",
                        f"config key `[perf] {k}` is declared but "
                        "fusion.CONFIG_KEYS does not accept it",
                        cfg_mod.tree))
                for k in sorted(set(schema) - perf_keys):
                    out.append(fmod.finding(
                        "SC317",
                        f"fusion.CONFIG_KEYS accepts `{k}` but "
                        "config.default_config() declares no "
                        f"`[perf] {k}`", fmod.tree))
        # extends SC309: an `execute_traced` override advertises the
        # kernel as trace-composable, but the planner's fusability gate
        # keys on cost() — without it the kernel silently never fuses
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                names = {b.name for b in node.body
                         if isinstance(b, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))}
                if "execute_traced" in names and "execute" in names \
                        and "cost" not in names:
                    out.append(mod.finding(
                        "SC317",
                        f"kernel `{node.name}` overrides "
                        "execute_traced (fusion trace hook) but "
                        "declares no cost() descriptor — the planner's "
                        "fusability gate keys on cost(), so this "
                        "kernel can never fuse; declare one or drop "
                        "the override", node))
        return out

    # -- SC306 / SC307 ---------------------------------------------------

    def _rpc_surface(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        registered: Dict[str, Tuple[ModuleInfo, ast.AST]] = {}
        for mod in project.modules:
            for name, node in _rpc_registrations(mod):
                registered[name] = (mod, node)
        if not registered:
            return out
        invoked: Set[str] = set()
        for mod in project.modules:
            for name, node in _rpc_invocations(mod):
                invoked.add(name)
                if name not in registered:
                    out.append(mod.finding(
                        "SC306",
                        f"RPC `{name}` is called here but no RpcServer "
                        "registers a handler for it (typo or dead "
                        "method?)", node))
        invoked |= set(_AUX_CALL_RE.findall(project.aux_source_text()))
        # indirection idiom: wait_for_server(addr, svc, method="Ping")
        # invokes via a parameter — count string defaults of args named
        # `method` as invocations
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                a = node.args
                pos = a.args[len(a.args) - len(a.defaults):]
                for arg, dflt in list(zip(pos, a.defaults)) + [
                        (ka, kd) for ka, kd in zip(a.kwonlyargs,
                                                   a.kw_defaults)
                        if kd is not None]:
                    if arg.arg == "method":
                        s = _const_str(dflt)
                        if s:
                            invoked.add(s)
        for name, (mod, node) in sorted(registered.items()):
            if name not in invoked:
                out.append(mod.finding(
                    "SC306",
                    f"RPC handler `{name}` is registered but never "
                    "invoked by any client in the repo (incl. tests/ "
                    "and tools/)", node))
        # SC307: classification table
        contracts: Optional[Dict[str, ast.AST]] = None
        cmod: Optional[ModuleInfo] = None
        for mod in project.modules:
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) \
                        == 1 and isinstance(stmt.targets[0], ast.Name) \
                        and stmt.targets[0].id == "RPC_CONTRACTS" \
                        and isinstance(stmt.value, ast.Dict):
                    contracts = {}
                    for k, v in zip(stmt.value.keys, stmt.value.values):
                        ks = _const_str(k)
                        if ks is None:
                            continue
                        contracts[ks] = k
                        # a present-but-incomplete entry is the same
                        # drift SC307 exists for: the classification
                        # must carry BOTH the deadline class and the
                        # idempotency verdict, as dict literals the
                        # lint can see
                        if not isinstance(v, ast.Dict):
                            out.append(mod.finding(
                                "SC307",
                                f"RPC_CONTRACTS entry `{ks}` is not a "
                                "dict literal — timeout/idempotency "
                                "must be statically checkable", v))
                            continue
                        have = {_const_str(vk) for vk in v.keys}
                        for want in ("timeout_s", "idempotent"):
                            if want not in have:
                                out.append(mod.finding(
                                    "SC307",
                                    f"RPC_CONTRACTS entry `{ks}` lacks "
                                    f"`{want}` (every handler needs a "
                                    "deadline class AND an idempotency "
                                    "verdict)", v))
                    cmod = mod
        if contracts is None:
            anchor_mod, anchor_node = next(iter(registered.values()))
            out.append(anchor_mod.finding(
                "SC307",
                "RPC handlers are registered but no RPC_CONTRACTS "
                "table declares their timeout/idempotency classes — "
                "the retry layer is flying blind", anchor_node))
            return out
        for name, (mod, node) in sorted(registered.items()):
            if name not in contracts:
                out.append(mod.finding(
                    "SC307",
                    f"RPC handler `{name}` has no RPC_CONTRACTS entry "
                    "(timeout class + idempotency)", node))
        for name in sorted(contracts):
            if name not in registered:
                assert cmod is not None
                out.append(cmod.finding(
                    "SC307",
                    f"RPC_CONTRACTS entry `{name}` matches no "
                    "registered handler", contracts[name]))
        return out
