"""Bounded-interleaving explorer for the protocol model.

Exhaustive breadth-first enumeration of every schedule of the enabled
transitions (analysis/model/protocol.py) up to a depth bound, with a
visited set over canonical states so the count is states-explored,
not schedules (the schedule count is the interesting bound — the
failover scenario yields ~10^4–10^5 distinct interleavings through
~10^3–10^4 states).

Invariants are checked at EVERY reachable state, not just quiescent
ones — the write-ahead invariant in particular only bites in the
window between an ack and a crash.  BFS + parent pointers means the
first violation found is a MINIMAL counterexample schedule, which the
report renders step by step.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .protocol import Config, State, enabled, invariants, scenario

__all__ = ["Violation", "Report", "explore", "explore_scenario"]

DEFAULT_DEPTH = 24
DEFAULT_MAX_STATES = 200_000


@dataclass
class Violation:
    invariant: str
    detail: str
    trace: List[str]          # minimal schedule: one label per step
    state: State

    def format(self) -> str:
        lines = [f"INVARIANT VIOLATED: {self.invariant}",
                 f"  {self.detail}",
                 f"  minimal schedule ({len(self.trace)} steps):"]
        for i, step in enumerate(self.trace, 1):
            lines.append(f"    {i:2d}. {step}")
        return "\n".join(lines)


@dataclass
class Report:
    scenario: str
    broken: Optional[str]
    states: int = 0
    edges: int = 0
    schedules: int = 0         # distinct interleavings within the bound
    max_depth_seen: int = 0
    exhausted: bool = True     # False if depth/state bound truncated
    violation: Optional[Violation] = None

    @property
    def ok(self) -> bool:
        return self.violation is None

    def to_dict(self) -> dict:
        d = {"scenario": self.scenario, "broken": self.broken,
             "states": self.states, "edges": self.edges,
             "schedules": self.schedules,
             "max_depth": self.max_depth_seen,
             "exhausted": self.exhausted, "ok": self.ok}
        if self.violation is not None:
            d["violation"] = {
                "invariant": self.violation.invariant,
                "detail": self.violation.detail,
                "trace": self.violation.trace,
            }
        return d


def _check(state: State, cfg: Config) -> Optional[Tuple[str, str]]:
    for name, inv in invariants(cfg):
        detail = inv(state, cfg)
        if detail is not None:
            return name, detail
    return None


def _trace(parents: Dict[State, Tuple[Optional[State], str]],
           state: State) -> List[str]:
    steps: List[str] = []
    cur: Optional[State] = state
    while cur is not None:
        parent, label = parents[cur]
        if parent is None:
            break
        steps.append(label)
        cur = parent
    steps.reverse()
    return steps


def explore(cfg: Config, initial: State, *,
            depth: int = DEFAULT_DEPTH,
            max_states: int = DEFAULT_MAX_STATES,
            scenario_name: str = "?",
            broken: Optional[str] = None) -> Report:
    """BFS over every interleaving; stops at the first violation (the
    minimal one, by BFS order) or when the frontier is exhausted."""
    report = Report(scenario=scenario_name, broken=broken)
    parents: Dict[State, Tuple[Optional[State], str]] = {
        initial: (None, "")}
    queue: "deque[tuple[State, int]]" = deque([(initial, 0)])
    succ: Dict[State, List[State]] = {}
    report.states = 1

    bad = _check(initial, cfg)
    if bad is not None:
        report.violation = Violation(bad[0], bad[1], [], initial)
        return report

    while queue:
        state, d = queue.popleft()
        report.max_depth_seen = max(report.max_depth_seen, d)
        kids = enabled(state, cfg)
        succ[state] = [nxt for _l, nxt in kids]
        for label, nxt in kids:
            report.edges += 1
            if nxt in parents:
                continue
            if d >= depth:
                # a genuinely new state past the bound: the space was
                # NOT exhausted (a leaf at the bound does not truncate)
                report.exhausted = False
                continue
            parents[nxt] = (state, label)
            report.states += 1
            bad = _check(nxt, cfg)
            if bad is not None:
                report.violation = Violation(
                    bad[0], bad[1], _trace(parents, nxt), nxt)
                return report
            if report.states >= max_states:
                report.exhausted = False
                return report
            queue.append((nxt, d + 1))

    report.schedules = _count_schedules(succ, initial, depth)
    return report


def _count_schedules(succ: Dict[State, List[State]], initial: State,
                     depth: int) -> int:
    """Distinct interleavings: level-by-level path DP over the explored
    graph (not a DAG — crash/restart genuinely cycles, so schedules are
    counted within the depth bound; a path that hits the bound counts
    as one truncated schedule)."""
    level: Dict[State, int] = {initial: 1}
    total = 0
    for _d in range(depth):
        nxt: Dict[State, int] = {}
        for s, n in level.items():
            kids = succ.get(s, ())
            if not kids:
                total += n          # terminal: one complete schedule
            for k in kids:
                nxt[k] = nxt.get(k, 0) + n
        if not nxt:
            return total
        level = nxt
    return total + sum(level.values())


def explore_scenario(name: str, broken: Optional[str] = None, *,
                     depth: int = DEFAULT_DEPTH,
                     max_states: int = DEFAULT_MAX_STATES) -> Report:
    cfg, initial = scenario(name, broken)
    return explore(cfg, initial, depth=depth, max_states=max_states,
                   scenario_name=name, broken=broken)
