"""scanner-model: bounded-interleaving checker for the control plane.

An abstract Master/Worker/Journal state machine (protocol.py) anchored
to the engine's RPC_CONTRACTS via RPC_ANCHORS — scanner-check SC406
pins model and source in sync both directions — explored exhaustively
over every schedule up to a depth bound (explorer.py), asserting at
every reachable state:

  I1  no acknowledged task is ever lost (write-ahead),
  I2  no committed task is ever double-applied (retry dedup),
  I3  no stale master mutates past the fence (generation monotonicity).

CLI: `python tools/scanner_model.py --scenario failover`.
Docs: docs/static-analysis.md (scanner-model section).
"""

from .protocol import (RPC_ANCHORS, Config, Record, SCENARIOS, State,
                       enabled, invariants, lineage, scenario)
from .explorer import (DEFAULT_DEPTH, DEFAULT_MAX_STATES, Report,
                       Violation, explore, explore_scenario)

__all__ = [
    "RPC_ANCHORS", "Config", "Record", "SCENARIOS", "State",
    "enabled", "invariants", "lineage", "scenario",
    "DEFAULT_DEPTH", "DEFAULT_MAX_STATES", "Report", "Violation",
    "explore", "explore_scenario",
]
