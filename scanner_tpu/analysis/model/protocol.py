"""Abstract Master/Worker/Journal protocol model (`scanner-model`).

A small state machine mirroring the control-plane protocol of
engine/{service,journal,shardmap}.py at the granularity its safety
story lives:

  * storage — a CAS generation cell (`claim_generation`), one journal
    segment per generation (appends by a superseded master land in its
    own dead segment), and a shard-map epoch cell;
  * masters — generation, a fence flag that LAGS the CAS (the
    `_check_fence` poll), volatile done/committed state, recovery that
    snapshots the predecessor's segment at takeover;
  * worker — pulls assignments, executes, reports `FinishedWork`,
    retries on reply loss (the RPC is idempotent=False — the master's
    done-set membership check is what makes the retry safe), latches
    generations monotonically.

`tools/scanner_model.py` explores every interleaving of the enabled
transitions (bounded BFS, analysis/model/explorer.py) and asserts the
three invariants the chaos drills sample dynamically
(docs/robustness.md):

  I1 write-ahead — at every reachable state, every acked completion
     (and the job-commit ack) has a journal record: `_journal_append`
     before the ack, on every path (scanner-check SC401).
  I2 no double-apply — the surviving journal lineage (takeover
     snapshot + the survivor's own segment) holds at most one done
     record per task and one commit record per job: the done-set
     dedup guard absorbs non-idempotent retries (SC402/SC312).
  I3 fencing — no record is authored by a master after it observed
     the fence, the claimed generation/map epoch only grow, and the
     shard map is owned by the surviving generation (SC403).

Transitions are anchored to RPC_CONTRACTS (engine/service.py) via
RPC_ANCHORS; scanner-check SC406 pins the two in sync both directions
so this model cannot rot away from the source.

`broken=` injects the defects the invariants exist to catch —
``ack_before_commit`` (ack outruns the group-commit; a crash between
them loses an acked completion), ``skip_dedup`` (retry of the
non-idempotent FinishedWork applies twice), ``ignore_fence`` (a
fenced master keeps mutating).  The explorer must find each with a
minimal counterexample schedule; tests/test_scanner_model.py pins it.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, List, Optional, Tuple

__all__ = ["RPC_ANCHORS", "Config", "State", "scenario", "SCENARIOS",
           "enabled", "invariants", "lineage", "Record"]

# model transition (the `t_<name>` functions below) -> RPC_CONTRACTS
# entry.  scanner-check SC406: every value must be a declared contract,
# every idempotent=False contract must appear here, and every key must
# name a defined transition.
RPC_ANCHORS = {
    "register_worker":   "RegisterWorker",
    "new_job":           "NewJob",
    "next_work":         "NextWork",
    "started_work":      "StartedWork",
    "finished_work":     "FinishedWork",
    "finished_batch":    "FinishedWorkBatch",
    "failed_work":       "FailedWork",
    "post_profile":      "PostProfile",
    "ship_spans":        "ShipSpans",
    "ship_memory":       "ShipMemoryReport",
    "gang_member_done":  "GangMemberDone",
    "gang_failed":       "GangFailed",
}

# journal record: (type, payload, author_gen, author_saw_fence)
Record = Tuple[str, object, int, bool]


@dataclass(frozen=True)
class MasterState:
    gen: int
    alive: bool = True
    fence_seen: bool = False
    recovered: bool = True       # False between claim and replay
    snapshot: Tuple[Record, ...] = ()   # predecessor records adopted
    done: FrozenSet[int] = frozenset()
    committed: bool = False
    admitted: bool = False
    telemetry: FrozenSet[str] = frozenset()   # volatile, once per kind
    gang_epoch: int = 0
    gang_acks: FrozenSet[int] = frozenset()
    pending: Tuple[Record, ...] = ()   # broken ack_before_commit only


@dataclass(frozen=True)
class State:
    storage_gen: int
    map_epoch: int
    map_owner: int                      # index into masters
    journals: Tuple[Tuple[Record, ...], ...]   # per generation, 1-based
    masters: Tuple[MasterState, ...]
    registered: bool = False
    # worker assignment: task -> (attempt, reported_failed)
    holding: Tuple[Tuple[int, int], ...] = ()
    acked: FrozenSet[int] = frozenset()
    commit_acked: bool = False
    executions: FrozenSet[Tuple[int, int]] = frozenset()  # (task, attempt)
    retries_left: int = 1
    strikes: FrozenSet[int] = frozenset()


@dataclass(frozen=True)
class Config:
    tasks: int = 1
    masters: int = 1
    failover: bool = False       # second master may claim + recover
    crash: bool = False          # first master may crash + restart
    gang: bool = False           # gang epoch fence transitions
    telemetry: bool = False      # PostProfile/ShipSpans/ShipMemoryReport
    batch: bool = False          # FinishedWorkBatch coalescing
    fail: bool = False           # FailedWork strike path
    retries: int = 1
    # reassignment bound: during the failover overlap a reassign/
    # dedup-absorb cycle (new master assigns, old unfenced master
    # absorbs the report) can repeat until the fence poll lands — real
    # and safe, but unbounded; capping attempts keeps the enumeration
    # exhaustive without hiding any distinct behavior
    max_attempts: int = 3
    # injected defects (tests/test_scanner_model.py)
    ack_before_commit: bool = False
    skip_dedup: bool = False
    ignore_fence: bool = False


SCENARIOS: Dict[str, Config] = {
    # single master, crash between any two transitions, restart replays
    # its own journal — the write-ahead (I1) and dedup (I2) kernel
    "crash": Config(tasks=2, masters=1, crash=True, retries=1,
                    fail=True),
    # two masters racing a generation bump: CAS claim, lagging fence
    # poll, takeover snapshot, worker retries — I1 + I2 + I3
    "failover": Config(tasks=1, masters=2, failover=True, retries=1),
    # gang epoch fence: member acks race an abort's epoch bump — a
    # stale-epoch report must never be applied
    "gang": Config(tasks=1, masters=1, gang=True, retries=1),
    # the batch/telemetry/strike surface on one master, no faults —
    # covers the remaining non-idempotent anchors exhaustively
    "surface": Config(tasks=2, masters=1, batch=True, telemetry=True,
                      fail=True, retries=1),
}


def scenario(name: str, broken: Optional[str] = None) -> "tuple[Config, State]":
    cfg = SCENARIOS[name]
    if broken is not None:
        if broken not in ("ack_before_commit", "skip_dedup",
                          "ignore_fence"):
            raise ValueError(f"unknown injected defect: {broken}")
        cfg = replace(cfg, **{broken: True})
    masters = [MasterState(gen=1)]
    for extra in range(1, cfg.masters):
        masters.append(MasterState(gen=1 + extra, alive=False,
                                   recovered=False))
    return cfg, State(
        storage_gen=1, map_epoch=1, map_owner=0,
        journals=tuple(() for _ in range(cfg.masters)),
        masters=tuple(masters), retries_left=cfg.retries)


# -- helpers ---------------------------------------------------------------


def _with_master(s: State, i: int, m: MasterState) -> State:
    ms = list(s.masters)
    ms[i] = m
    return replace(s, masters=tuple(ms))


def _append(s: State, i: int, rec_type: str, payload: object) -> State:
    """Group-commit one record to master i's own segment — mirrors
    `_journal_append`: a master that has SEEN the fence journals
    nothing (ignore_fence drops that guard)."""
    m = s.masters[i]
    rec: Record = (rec_type, payload, m.gen, m.fence_seen)
    js = list(s.journals)
    js[m.gen - 1] = js[m.gen - 1] + (rec,)
    return replace(s, journals=tuple(js))


def _live(s: State, cfg: Config, i: int) -> bool:
    m = s.masters[i]
    return m.alive and m.recovered


def _handler_gate(s: State, cfg: Config, i: int) -> bool:
    """The `_fenced` wrapper: a master that observed the fence NACKs
    every mutation (ignore_fence models losing the guard)."""
    m = s.masters[i]
    if m.fence_seen and not cfg.ignore_fence:
        return False
    return True


def lineage(s: State) -> Tuple[Record, ...]:
    """The surviving journal as recovery reads it: the survivor's
    takeover snapshot plus its own segment."""
    surv = max(range(len(s.masters)),
               key=lambda i: s.masters[i].gen
               if s.masters[i].gen <= s.storage_gen else -1)
    m = s.masters[surv]
    return m.snapshot + s.journals[m.gen - 1]


# -- transitions -----------------------------------------------------------
#
# each t_<name>(s, cfg) returns [(detail, next_state), ...] — every
# enabled instantiation.  Names are pinned to RPC_ANCHORS (SC406);
# internal (non-RPC) steps carry no anchor.


def t_register_worker(s: State, cfg: Config):
    if s.registered:
        return []
    out = []
    for i in range(len(s.masters)):
        if _live(s, cfg, i) and _handler_gate(s, cfg, i):
            out.append((f"worker registers with m{i}",
                        replace(s, registered=True)))
    return out


def t_new_job(s: State, cfg: Config):
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or m.admitted \
                or not _handler_gate(s, cfg, i):
            continue
        ns = _append(s, i, "admit", None)
        out.append((f"m{i} admits the bulk (journal reset + admit "
                    "record)",
                    _with_master(ns, i, replace(m, admitted=True))))
    return out


def t_next_work(s: State, cfg: Config):
    if not s.registered:
        return []
    out = []
    held = dict(s.holding)
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or not m.admitted \
                or not _handler_gate(s, cfg, i):
            continue
        for task in range(cfg.tasks):
            if task in m.done or task in held:
                continue
            attempt = max([a for t, a in s.executions if t == task],
                          default=0) + 1
            if attempt > cfg.max_attempts:
                continue
            out.append((f"m{i} assigns task {task} (attempt {attempt})",
                        replace(s, holding=tuple(sorted(
                            list(s.holding) + [(task, attempt)])))))
    return out


def t_started_work(s: State, cfg: Config):
    # lease bookkeeping is volatile; modeled as a no-op ack so the
    # anchor exists — a fenced master still NACKs it
    return []


def _apply_finished(s: State, cfg: Config, i: int, task: int,
                    attempt: int) -> List[Tuple[str, State]]:
    """FinishedWork handler body: dedup -> journal -> apply -> ack,
    with the injected-defect orderings."""
    m = s.masters[i]
    executed = replace(
        s, executions=s.executions | {(task, attempt)})
    if task in m.done and not cfg.skip_dedup:
        # duplicate (retry) absorbed by done-set membership: ack
        # without a second apply
        ns = replace(executed,
                     holding=tuple((t, a) for t, a in s.holding
                                   if t != task),
                     acked=s.acked | {task})
        return [(f"m{i} absorbs duplicate task {task}", ns)]
    if cfg.ack_before_commit:
        # INJECTED DEFECT: ack first, group-commit later (t_flush) —
        # a crash in between loses an acked completion
        ns = _with_master(executed, i,
                          replace(m, done=m.done | {task},
                                  pending=m.pending
                                  + (("done", task, m.gen,
                                      m.fence_seen),)))
        ns = replace(ns,
                     holding=tuple((t, a) for t, a in ns.holding
                                   if t != task),
                     acked=ns.acked | {task})
        return [(f"m{i} ACKS task {task} before the commit", ns)]
    ns = _append(executed, i, "done", task)
    ns = _with_master(ns, i, replace(m, done=m.done | {task}))
    acked = replace(ns,
                    holding=tuple((t, a) for t, a in ns.holding
                                  if t != task),
                    acked=ns.acked | {task})
    out = [(f"m{i} commits+acks task {task}", acked)]
    if s.retries_left > 0:
        # reply lost after the apply: the worker still holds the task
        # and will retry the (non-idempotent) RPC
        lost = replace(ns, retries_left=s.retries_left - 1)
        out.append((f"m{i} commits task {task} but the ack is lost "
                    "(worker will retry)", lost))
    return out


def t_finished_work(s: State, cfg: Config):
    out = []
    for i in range(len(s.masters)):
        if not _live(s, cfg, i) or not s.masters[i].admitted \
                or not _handler_gate(s, cfg, i):
            continue
        for task, attempt in s.holding:
            out.extend(_apply_finished(s, cfg, i, task, attempt))
    return out


def t_finished_batch(s: State, cfg: Config):
    """Coalesced completion (FinishedWorkBatch): every held task lands
    in ONE group-commit, then all are acked."""
    if not cfg.batch or len(s.holding) < 2:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or not m.admitted \
                or not _handler_gate(s, cfg, i):
            continue
        ns = s
        fresh = []
        for task, attempt in s.holding:
            ns = replace(ns, executions=ns.executions
                         | {(task, attempt)})
            if task not in m.done or cfg.skip_dedup:
                fresh.append(task)
                ns = _append(ns, i, "done", task)
        m2 = replace(ns.masters[i], done=m.done | set(fresh))
        ns = _with_master(ns, i, m2)
        ns = replace(ns, holding=(),
                     acked=ns.acked | {t for t, _a in s.holding})
        out.append((f"m{i} batch-commits tasks "
                    f"{sorted(t for t, _a in s.holding)}", ns))
    return out


def t_failed_work(s: State, cfg: Config):
    if not cfg.fail:
        return []
    out = []
    for i in range(len(s.masters)):
        if not _live(s, cfg, i) or not s.masters[i].admitted \
                or not _handler_gate(s, cfg, i):
            continue
        for task, attempt in s.holding:
            if task in s.strikes:
                continue  # one strike per task bounds the space
            ns = _append(s, i, "strike", task)
            ns = replace(ns,
                         holding=tuple((t, a) for t, a in s.holding
                                       if t != task),
                         strikes=ns.strikes | {task},
                         executions=ns.executions | {(task, attempt)})
            out.append((f"m{i} journals a strike for task {task} "
                        "(requeued)", ns))
    return out


def _telemetry(s: State, cfg: Config, kind: str):
    if not cfg.telemetry:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or kind in m.telemetry \
                or not _handler_gate(s, cfg, i):
            continue
        out.append((f"m{i} accepts {kind}",
                    _with_master(s, i, replace(
                        m, telemetry=m.telemetry | {kind}))))
    return out


def t_post_profile(s: State, cfg: Config):
    return _telemetry(s, cfg, "profile")


def t_ship_spans(s: State, cfg: Config):
    return _telemetry(s, cfg, "spans")


def t_ship_memory(s: State, cfg: Config):
    return _telemetry(s, cfg, "memory")


def t_gang_member_done(s: State, cfg: Config):
    """Member ack stamped with an epoch: the handler applies it only
    at the LIVE epoch (exact match — `_gang_for_req_locked`), so a
    pre-abort straggler can never land."""
    if not cfg.gang:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or not _handler_gate(s, cfg, i):
            continue
        for stamped in range(m.gang_epoch + 1):
            if stamped in m.gang_acks:
                continue
            if stamped != m.gang_epoch and not cfg.ignore_fence:
                out.append((f"m{i} NACKs stale gang ack "
                            f"(epoch {stamped} != {m.gang_epoch})", s))
                continue
            # payload records (stamped, live-at-apply): I3 flags any
            # apply where the two differ — a stale straggler landing
            ns = _append(s, i, "gang", (stamped, m.gang_epoch))
            out.append((f"m{i} applies gang ack at epoch {stamped}",
                        _with_master(ns, i, replace(
                            m, gang_acks=m.gang_acks | {stamped}))))
    return out


def t_gang_failed(s: State, cfg: Config):
    if not cfg.gang:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or not _handler_gate(s, cfg, i) \
                or m.gang_epoch >= 1:
            continue  # one abort bounds the space
        ns = _append(s, i, "gang_abort", m.gang_epoch)
        out.append((f"m{i} aborts the gang (epoch "
                    f"{m.gang_epoch} -> {m.gang_epoch + 1})",
                    _with_master(ns, i, replace(
                        m, gang_epoch=m.gang_epoch + 1))))
    return out


# -- internal (non-RPC) steps ---------------------------------------------


def i_flush_pending(s: State, cfg: Config):
    """The delayed group-commit of the ack_before_commit defect."""
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not m.alive or not m.pending:
            continue
        js = list(s.journals)
        js[m.gen - 1] = js[m.gen - 1] + m.pending
        ns = replace(s, journals=tuple(js))
        out.append((f"m{i} flushes its pending journal records",
                    _with_master(ns, i, replace(m, pending=()))))
    return out


def i_claim(s: State, cfg: Config):
    """Successor CAS-claims the next generation and bumps the shard
    map epoch (claim_generation + ShardMap.publish) — the predecessor
    keeps running until its fence poll."""
    if not cfg.failover:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if m.alive or m.gen != s.storage_gen + 1:
            continue
        out.append((f"m{i} claims generation {m.gen} (CAS) and "
                    "publishes the shard map",
                    replace(_with_master(s, i, replace(m, alive=True)),
                            storage_gen=m.gen,
                            map_epoch=s.map_epoch + 1, map_owner=i)))
    return out


def i_recover(s: State, cfg: Config):
    """Takeover replay: snapshot the predecessor's segment as of NOW
    and fold it (idempotent by construction — _apply_journal_records);
    records the predecessor appends later land in a dead segment."""
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not m.alive or m.recovered or m.gen != s.storage_gen:
            continue
        snap = ()
        for g in range(m.gen - 1, 0, -1):
            if s.journals[g - 1]:
                snap = s.journals[g - 1]
                break
        done = frozenset(p for t, p, _g, _f in snap if t == "done")
        admitted = any(t == "admit" for t, _p, _g, _f in snap)
        committed = any(t == "commit" for t, _p, _g, _f in snap)
        out.append((f"m{i} recovers: replays {len(snap)} predecessor "
                    "records",
                    _with_master(s, i, replace(
                        m, recovered=True, snapshot=snap, done=done,
                        admitted=admitted or m.admitted,
                        committed=committed))))
    return out


def i_poll_fence(s: State, cfg: Config):
    """_check_fence: a predecessor eventually observes the claim."""
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if m.alive and not m.fence_seen and m.gen < s.storage_gen:
            out.append((f"m{i} polls storage and observes the fence "
                        f"(generation {s.storage_gen} claimed)",
                        _with_master(s, i, replace(
                            m, fence_seen=True))))
    return out


def i_crash(s: State, cfg: Config):
    if not cfg.crash:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if m.alive and m.gen == s.storage_gen and m.admitted:
            out.append((f"m{i} CRASHES (volatile state wiped)",
                        _with_master(s, i, replace(
                            m, alive=False, recovered=False, pending=(),
                            done=frozenset(), committed=False,
                            telemetry=frozenset()))))
    return out


def i_restart(s: State, cfg: Config):
    if not cfg.crash:
        return []
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if m.alive or m.gen != s.storage_gen:
            continue
        seg = s.journals[m.gen - 1]
        done = frozenset(p for t, p, _g, _f in seg if t == "done")
        out.append((f"m{i} restarts: replays its own journal "
                    f"({len(seg)} records)",
                    _with_master(s, i, replace(
                        m, alive=True, recovered=True, done=done,
                        admitted=any(t == "admit"
                                     for t, _p, _g, _f in seg),
                        committed=any(t == "commit"
                                      for t, _p, _g, _f in seg)))))
    return out


def i_commit_job(s: State, cfg: Config):
    """_maybe_finish_job: all tasks done -> journal the commit record,
    then the completion becomes client-visible (commit_acked)."""
    out = []
    for i in range(len(s.masters)):
        m = s.masters[i]
        if not _live(s, cfg, i) or not m.admitted or m.committed \
                or not _handler_gate(s, cfg, i):
            continue
        if len(m.done) != cfg.tasks:
            continue
        if cfg.ack_before_commit:
            ns = _with_master(s, i, replace(
                m, committed=True,
                pending=m.pending + (("commit", None, m.gen,
                                      m.fence_seen),)))
            out.append((f"m{i} ACKS the job commit before journaling "
                        "it", replace(ns, commit_acked=True)))
            continue
        ns = _append(s, i, "commit", None)
        ns = _with_master(ns, i, replace(m, committed=True))
        out.append((f"m{i} journals the job commit and publishes "
                    "completion", replace(ns, commit_acked=True)))
    return out


_TRANSITIONS = [
    t_register_worker, t_new_job, t_next_work, t_started_work,
    t_finished_work, t_finished_batch, t_failed_work,
    t_post_profile, t_ship_spans, t_ship_memory,
    t_gang_member_done, t_gang_failed,
    i_flush_pending, i_claim, i_recover, i_poll_fence,
    i_crash, i_restart, i_commit_job,
]


def enabled(s: State, cfg: Config) -> List[Tuple[str, State]]:
    """Every enabled (label, successor) pair — the explorer's branch
    set.  Self-loops (NACK replies) are dropped: they change nothing
    and would make every schedule infinite."""
    out: List[Tuple[str, State]] = []
    for t in _TRANSITIONS:
        for label, ns in t(s, cfg):
            if ns != s:
                out.append((label, ns))
    return out


# -- invariants ------------------------------------------------------------


def _journaled(s: State, rec_type: str, payload: object) -> bool:
    # pending (un-flushed) records do NOT count: a crash wipes them
    for seg in s.journals:
        for t, p, _g, _f in seg:
            if t == rec_type and p == payload:
                return True
    return False


def inv_write_ahead(s: State, cfg: Config) -> Optional[str]:
    """I1: an acked completion is never lost — the journal record must
    exist at the instant of the ack (`_journal_append` docstring)."""
    for task in sorted(s.acked):
        if not _journaled(s, "done", task):
            return (f"task {task} was ACKED but no done-record is in "
                    "any journal — a crash here loses an acked "
                    "completion (write-ahead violated)")
    if s.commit_acked and not _journaled(s, "commit", None):
        return ("the job commit was published but no commit record "
                "is journaled — a crash here un-finishes a finished "
                "job")
    return None


def inv_no_double_apply(s: State, cfg: Config) -> Optional[str]:
    """I2: the surviving lineage applies each record once."""
    lin = lineage(s)
    done_seen = set()
    commits = 0
    for t, p, _g, _f in lin:
        if t == "done":
            if p in done_seen:
                return (f"task {p} has TWO done-records in the "
                        "surviving journal lineage — a retried "
                        "non-idempotent FinishedWork was applied "
                        "twice (dedup guard lost)")
            done_seen.add(p)
        elif t == "commit":
            commits += 1
            if commits > 1:
                return ("two commit records in the surviving "
                        "lineage — the job double-committed")
    return None


def inv_fencing(s: State, cfg: Config) -> Optional[str]:
    """I3: no mutation by a master that observed the fence; claimed
    generation and map epoch only grow; the survivor owns the map."""
    for seg in s.journals:
        for t, p, g, fenced in seg:
            if fenced:
                return (f"a `{t}` record was journaled by generation "
                        f"{g} AFTER it observed the fence — a "
                        "superseded master kept mutating")
            if t == "gang" and p[0] != p[1]:
                return (f"a gang ack stamped epoch {p[0]} was applied "
                        f"at live epoch {p[1]} — a pre-abort "
                        "straggler landed past the epoch fence")
    for i, m in enumerate(s.masters):
        if m.fence_seen and m.gen >= s.storage_gen:
            return (f"m{i} observed a fence for its own live "
                    "generation — the CAS cell went backwards")
    surv = max((m.gen, i) for i, m in enumerate(s.masters)
               if m.gen <= s.storage_gen)[1]
    if s.masters[surv].alive and s.masters[surv].recovered \
            and s.map_owner != surv and s.storage_gen > 1:
        return (f"the shard map is owned by m{s.map_owner} but "
                f"generation {s.storage_gen} (m{surv}) survived — a "
                "stale publish landed")
    return None


def invariants(cfg: Config):
    return [("I1-write-ahead", inv_write_ahead),
            ("I2-no-double-apply", inv_no_double_apply),
            ("I3-fencing", inv_fencing)]
