"""Stream sampling/spacing/slicing DSL and IO binding.

Capability parity: reference scannerpy/streams.py (StreamsGenerator) and
io.py (sc.io.Input/Output), plus partitioner.py (TaskPartitioner).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ..common import GraphException, SliceList
from . import ops as O


def _norm_range(a) -> Dict[str, int]:
    if isinstance(a, dict):
        return {"start": int(a["start"]), "end": int(a["end"]),
                **({"stride": int(a["stride"])} if "stride" in a else {})}
    if isinstance(a, (tuple, list)) and len(a) in (2, 3):
        d = {"start": int(a[0]), "end": int(a[1])}
        if len(a) == 3:
            d["stride"] = int(a[2])
        return d
    raise GraphException(f"bad range spec: {a!r}")


def _per_stream(args, f):
    """Apply normalizer f per stream, passing SliceList through per-group."""
    out = []
    for a in args:
        if isinstance(a, SliceList):
            out.append(SliceList(f(x) for x in a))
        else:
            out.append(f(a))
    return out


class StreamsGenerator:
    """sc.streams.* — sampling ops (reference streams.py:8)."""

    def Slice(self, input: O.OpColumn, partitions: Sequence[Dict]
              ) -> O.OpColumn:
        # partitions are dicts {"kind": ..., **args} built by TaskPartitioner
        kinds = {p["kind"] for p in partitions}
        if len(kinds) != 1:
            raise GraphException("all streams must use the same partitioner")
        node = O.OpNode(O.SLICE_OP, {"col": input}, extra={
            "partitioner_kind": kinds.pop(),
            "args_per_stream": [
                {k: v for k, v in p.items() if k != "kind"}
                for p in partitions]})
        return node.outputs[0]

    def Unslice(self, input: O.OpColumn) -> O.OpColumn:
        return O.OpNode(O.UNSLICE_OP, {"col": input}).outputs[0]

    def _sample(self, input: O.OpColumn, kind: str, args_per_stream
                ) -> O.OpColumn:
        node = O.OpNode(O.SAMPLE_OP, {"col": input}, extra={
            "sampler_kind": kind, "args_per_stream": args_per_stream})
        return node.outputs[0]

    def _space(self, input: O.OpColumn, kind: str, args_per_stream
               ) -> O.OpColumn:
        node = O.OpNode(O.SPACE_OP, {"col": input}, extra={
            "sampler_kind": kind, "args_per_stream": args_per_stream})
        return node.outputs[0]

    def All(self, input: O.OpColumn) -> O.OpColumn:
        # identity; still an op so per-stream arg counts line up
        return self._sample(input, "All", None)

    def Stride(self, input: O.OpColumn, strides: Sequence) -> O.OpColumn:
        def norm(a):
            return {"stride": int(a["stride"] if isinstance(a, dict) else a)}
        return self._sample(input, "Strided", _per_stream(strides, norm))

    def Range(self, input: O.OpColumn, ranges: Sequence) -> O.OpColumn:
        def norm(a):
            d = _norm_range(a)
            return {"starts": [d["start"]], "ends": [d["end"]], "stride": 1}
        return self._sample(input, "StridedRanges", _per_stream(ranges, norm))

    def Ranges(self, input: O.OpColumn, intervals: Sequence) -> O.OpColumn:
        def norm(iv):
            rs = [_norm_range(x) for x in iv]
            return {"starts": [r["start"] for r in rs],
                    "ends": [r["end"] for r in rs], "stride": 1}
        return self._sample(input, "StridedRanges",
                            _per_stream(intervals, norm))

    def StridedRange(self, input: O.OpColumn, ranges: Sequence) -> O.OpColumn:
        def norm(a):
            d = _norm_range(a)
            return {"starts": [d["start"]], "ends": [d["end"]],
                    "stride": d.get("stride", 1)}
        return self._sample(input, "StridedRanges", _per_stream(ranges, norm))

    def StridedRanges(self, input: O.OpColumn, intervals: Sequence = None,
                      stride: int = 1) -> O.OpColumn:
        if intervals is None:
            raise GraphException(
                "StridedRanges requires intervals (one list per stream)")
        def norm(iv):
            rs = [_norm_range(x) for x in iv]
            return {"starts": [r["start"] for r in rs],
                    "ends": [r["end"] for r in rs], "stride": stride}
        return self._sample(input, "StridedRanges",
                            _per_stream(intervals, norm))

    def Gather(self, input: O.OpColumn, indices: Sequence[Sequence[int]],
               **kw) -> O.OpColumn:
        def norm(rows):
            return {"rows": [int(r) for r in rows]}
        return self._sample(input, "Gather", _per_stream(indices, norm))

    def RepeatNull(self, input: O.OpColumn, spacings: Sequence) -> O.OpColumn:
        def norm(a):
            return {"spacing": int(a)}
        return self._space(input, "SpaceNull", _per_stream(spacings, norm))

    def Repeat(self, input: O.OpColumn, spacings: Sequence) -> O.OpColumn:
        def norm(a):
            return {"spacing": int(a)}
        return self._space(input, "SpaceRepeat", _per_stream(spacings, norm))


class TaskPartitioner:
    """sc.partitioner.* — slice partition specs (reference partitioner.py).
    Returns plain dicts consumed by streams.Slice."""

    DEFAULT_GROUP_SIZE = 250

    def all(self, group_size: int = DEFAULT_GROUP_SIZE) -> Dict:
        return self.strided(1, group_size)

    def strided(self, stride: int,
                group_size: int = DEFAULT_GROUP_SIZE) -> Dict:
        return {"kind": "Strided", "stride": stride, "group_size": group_size}

    def range(self, start: int, end: int) -> Dict:
        return self.ranges([(start, end)])

    def ranges(self, intervals) -> Dict:
        return self.strided_ranges(intervals, 1)

    def strided_range(self, start: int, end: int, stride: int) -> Dict:
        return self.strided_ranges([(start, end)], stride)

    def strided_ranges(self, intervals, stride: int = 1) -> Dict:
        return {"kind": "StridedRange",
                "starts": [int(i[0]) for i in intervals],
                "ends": [int(i[1]) for i in intervals],
                "stride": stride}

    def gather(self, groups: Sequence[Sequence[int]]) -> Dict:
        return {"kind": "Gather", "groups": [list(g) for g in groups]}


class IOGenerator:
    """sc.io.Input / sc.io.Output (reference io.py:4-24)."""

    def __init__(self, sc=None):
        self._sc = sc

    def Input(self, streams: Sequence) -> O.OpColumn:
        if not streams:
            raise GraphException("io.Input needs at least one stream")
        node = O.OpNode(O.INPUT_OP, {}, extra={"streams": list(streams)})
        node.outputs[0].is_frame = bool(
            getattr(streams[0], "is_video", False))
        return node.outputs[0]

    def Output(self, op: Union[O.OpColumn, O.OpNode],
               streams: Sequence) -> O.OpNode:
        if isinstance(op, O.OpNode):
            if len(op.outputs) != 1:
                raise GraphException(
                    "io.Output needs a single column; select one")
            op = op.outputs[0]
        node = O.OpNode(O.OUTPUT_OP, {"col": op},
                        extra={"streams": list(streams),
                               "encode_options": dict(op.encode_options)})
        return node
