"""Whole-pipeline XLA fusion planning: chains of fusable device ops.

Scanner's evaluate stage is a per-op pipeline, and the PR 9 compile
ledger + roofline gauges measure exactly what that costs: every device
op is its own jitted call, so op boundaries are dispatch/sync points and
memory-bound neighbors (Resize, Blur, HistDiff) round-trip their
intermediates through HBM when XLA could fuse them away entirely.  This
module is the planning half of ROADMAP item 3 — in the spirit of
"Automatic Full Compilation of Julia Programs and ML Models to Cloud
TPUs" (PAPERS.md), lower the whole chain to one XLA program so op
boundaries become fusion candidates:

  * ``plan_chains`` walks a ``GraphInfo`` and groups maximal runs of
    fusable device ops.  A node is fusable when it is a stateless,
    non-variadic, batched (batch > 1) single-input/single-output TPU
    kernel whose class declares a ``cost()`` descriptor (the hook both
    feeds the fuse decision and marks the execute body as
    trace-composable — see ``Kernel.execute_traced``).  Host/python
    ops, stateful kernels, and explicit ``fuse=False`` node overrides
    break chains.  A chain extends only while its tail has exactly ONE
    consumer (an intermediate read by anything else must materialize,
    so it becomes the chain's tail instead).
  * The fuse decision is cost-driven: when the roofline ledger
    (util/coststats.py ``op_efficiency``) already classified EVERY
    member of a candidate chain as compute-bound, fusion cannot save
    HBM traffic and the chain stays staged (a fresh compile for no
    bandwidth win); any memory-bound (or not-yet-measured) member makes
    the chain worth one fused executable.
  * Stencil members fuse by composing their window math into the
    chain's input stencil: the chain's read window is the composition
    of member windows, with REPEAT_EDGE clamping applied at every
    level exactly as the staged backward dilation
    (graph/analysis.py ``derive_task_streams``) applies it.

The execution half — ``FusedKernelInstance`` composing the member
``execute_traced`` bodies into one jitted program per bucket — lives in
engine/evaluate.py.

``SCANNER_TPU_FUSION=0`` is the kill switch / A/B lever; the ``[perf]
fusion_enabled`` / ``fusion_min_chain`` config keys carry deployment
defaults (docs/guide.md).  docs/observability.md §Fusion catalogs the
series below (scanner-check SC317 pins both contracts).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..common import DeviceType
from ..util import metrics as _mx
from ..util import tracing as _tracing
from ..util.log import get_logger
from . import ops as O

_log = get_logger("fusion")

# the SC317 contract: this tuple, the series registered below, and the
# marker-delimited table in docs/observability.md §Fusion may not drift
# (all pairings, both directions)
FUSION_SERIES = (
    "scanner_tpu_fusion_chains_planned",
    "scanner_tpu_fusion_chain_flops_per_s",
    "scanner_tpu_fusion_chain_bytes_per_s",
    "scanner_tpu_fusion_intermediate_bytes_saved_total",
)

# the [perf] fusion_* config keys config.default_config() must declare
# — exactly these (scanner-check SC317, both directions)
CONFIG_KEYS = ("fusion_enabled", "fusion_min_chain")

_M_CHAINS = _mx.registry().gauge(
    "scanner_tpu_fusion_chains_planned",
    "Member count of each fused chain the planner formed (one labeled "
    "sample per chain id; 0 chains planned leaves the series empty).",
    labels=["chain"])
_M_CHAIN_FLOPS = _mx.registry().gauge(
    "scanner_tpu_fusion_chain_flops_per_s",
    "Achieved FLOP/s of a fused chain's measured calls (member cost() "
    "descriptors summed, joined with measured seconds), per chain id, "
    "device and bucket.",
    labels=["chain", "device", "bucket"])
_M_CHAIN_BW = _mx.registry().gauge(
    "scanner_tpu_fusion_chain_bytes_per_s",
    "Achieved HBM bandwidth of a fused chain's measured calls — the "
    "chain reads its head input and writes its tail output; "
    "intermediates never materialize — per chain id, device and "
    "bucket.",
    labels=["chain", "device", "bucket"])
_M_BYTES_SAVED = _mx.registry().counter(
    "scanner_tpu_fusion_intermediate_bytes_saved_total",
    "Intermediate HBM traffic (member output writes + next-member "
    "input reads, from the member cost() descriptors) that fused "
    "dispatch avoided materializing, per chain id and device.",
    labels=["chain", "device"])


# -- knobs ------------------------------------------------------------------

# same env semantics as SCANNER_TPU_FRAME_CACHE (one parser, no drift);
# SCANNER_TPU_FUSION=0 is the A/B kill switch
_ENABLED = _tracing._env_on("SCANNER_TPU_FUSION")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Programmatic override ([perf] fusion_enabled config key, tests,
    bench A/B); the SCANNER_TPU_FUSION env var is read at import and
    wins when set (call sites guard on it)."""
    global _ENABLED
    _ENABLED = bool(on)


_MIN_CHAIN = 2


def fusion_min_chain() -> int:
    return _MIN_CHAIN


def set_min_chain(n: int) -> None:
    """[perf] fusion_min_chain config wiring: minimum member count for
    a chain to fuse (< 2 is meaningless — a singleton IS the staged
    path)."""
    global _MIN_CHAIN
    _MIN_CHAIN = max(2, int(n))


# -- the planner ------------------------------------------------------------

@dataclass
class FusionChain:
    """One maximal run of fusable ops, head -> tail in dataflow order.
    Only the tail's output materializes; the engine composes the member
    execute bodies into one jitted program (FusedKernelInstance)."""

    members: List[O.OpNode]

    @property
    def head(self) -> O.OpNode:
        return self.members[0]

    @property
    def tail(self) -> O.OpNode:
        return self.members[-1]

    @property
    def chain_id(self) -> str:
        """The stable chain identity observability keys on: the member
        op names joined with '+' (e.g. "Resize+Blur+Histogram")."""
        return "+".join(m.name for m in self.members)

    @property
    def member_names(self) -> List[str]:
        return [m.name for m in self.members]

    def stencils(self) -> List[List[int]]:
        return [m.effective_stencil() for m in self.members]

    def windows(self) -> List[int]:
        """Per-member stencil-window length; 0 = the member takes no
        window axis (stencil [0]).  Note a 1-offset stencil like [-1]
        still carries a window axis of length 1."""
        return [len(s) if s != [0] else 0 for s in self.stencils()]

    def width(self) -> int:
        """Total read-window expansion of the composed chain stencil:
        one tail row reads `width` head-input positions."""
        w = 1
        for win in self.windows():
            w *= max(win, 1)
        return w


def fusable(node: O.OpNode) -> bool:
    """Chain eligibility for one node.  The ``cost()``-override
    requirement is load-bearing twice over: the planner needs the
    descriptor for the fuse decision and the chain-level roofline
    gauges, and declaring it marks the kernel's execute body as
    trace-composable (SC317 enforces the pairing with
    ``execute_traced`` overrides)."""
    if node.is_builtin or node.spec is None:
        return False
    if node.fuse is False:
        return False
    spec = node.spec
    if spec.is_stateful or spec.variadic:
        return False
    if node.warmup is not None:
        return False
    if node.effective_device() != DeviceType.TPU:
        return False
    if node.effective_batch() <= 1:
        return False
    if len(spec.input_columns) != 1 or len(spec.output_columns) != 1:
        return False
    fac = spec.kernel_factory
    if fac is None or getattr(fac, "cost", None) is O.Kernel.cost:
        return False
    return True


def _ledger_probe(node: O.OpNode) -> Optional[str]:
    """Roofline verdict for one op from the live ledger: "compute" /
    "memory" when every measured (device, bucket) row of the op agrees
    or any row is memory-bound, None when the op was never measured."""
    try:
        from ..util import coststats as _cs
        rows = _cs.op_efficiency()
    except Exception:  # noqa: BLE001 — planning must never fail a job
        return None
    bounds = {r["bound"] for r in rows if r["op"] == node.name}
    if not bounds:
        return None
    if "memory" in bounds:
        return "memory"
    return "compute"


def plan_chains(info, min_chain: Optional[int] = None,
                probe: Optional[Callable[[O.OpNode], Optional[str]]]
                = None) -> List[FusionChain]:
    """Group maximal runs of fusable ops in `info` (a GraphInfo) into
    FusionChains.  `min_chain` defaults to the configured
    [perf] fusion_min_chain; `probe` defaults to the roofline-ledger
    verdict (tests inject their own)."""
    if min_chain is None:
        min_chain = fusion_min_chain()
    if probe is None:
        probe = _ledger_probe
    chains: List[FusionChain] = []
    used: set = set()
    for n in info.ops:
        if n.id in used or not fusable(n):
            continue
        # topo order reaches the head of every maximal run first: a
        # fusable producer with this node as its single consumer would
        # already have absorbed it into `used`
        members = [n]
        used.add(n.id)
        cur = n
        while True:
            cons = info.consumers.get(cur.id, [])
            if len(cons) != 1:
                break  # externally consumed (or a sink): cur is the tail
            nxt = info.op_at(cons[0])
            if nxt.id in used or not fusable(nxt):
                break
            # a windowed op may only HEAD a chain: as the head its
            # stencil composes into the chain's input gather (same rows
            # the staged path read), but mid-chain the window would make
            # the fused program recompute every upstream member once per
            # window element — the staged stencil cache computes each
            # intermediate row exactly once, so fusing across it loses.
            sten = nxt.effective_stencil()
            if sten != [0]:
                break
            members.append(nxt)
            used.add(nxt.id)
            cur = nxt
        if len(members) < max(2, int(min_chain)):
            continue
        # cost-driven no-fuse: when the ledger already judged EVERY
        # member compute-bound, fusing saves no HBM traffic — skip the
        # fresh chain compile.  Any memory-bound or unmeasured member
        # keeps the chain.
        verdicts = [probe(m) for m in members]
        if all(v == "compute" for v in verdicts):
            _log.debug("chain %s stays staged: all members compute-bound",
                       "+".join(m.name for m in members))
            continue
        ch = FusionChain(members=members)
        chains.append(ch)
        _M_CHAINS.labels(chain=ch.chain_id).set(len(members))
    return chains


def chain_metrics_for(chain_id: str, device: str, bucket: int,
                      cls: Dict, saved_bytes: float) -> None:
    """Refresh the chain-level roofline gauges from one measured fused
    call's cumulative classification (coststats.classify shape)."""
    b = str(int(bucket))
    _M_CHAIN_FLOPS.labels(chain=chain_id, device=device, bucket=b).set(
        cls["flops_per_s"])
    _M_CHAIN_BW.labels(chain=chain_id, device=device, bucket=b).set(
        cls["bytes_per_s"])
    if saved_bytes > 0:
        _M_BYTES_SAVED.labels(chain=chain_id, device=device).inc(
            saved_bytes)
