"""Row-domain samplers and partitioners.

Capability parity: reference scanner/engine/sampler.{h,cpp} — DomainSampler
(sampler.h:39, impls sampler.cpp:33-454) and Partitioner (sampler.h:76, impls
sampler.cpp:505-742).  Semantics are bit-for-bit the reference's; the
implementation is vectorized numpy instead of per-row C++ loops.

A DomainSampler maps between a downstream (sampled) row domain and its
upstream domain.  A Partitioner splits an upstream domain into ordered groups
of rows (slice groups).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..common import GraphException


class DomainSampler:
    name = "Default"

    def upstream_rows(self, downstream_rows: np.ndarray) -> np.ndarray:
        """Minimal upstream rows needed to produce `downstream_rows`
        (sorted unique)."""
        raise NotImplementedError

    def num_downstream(self, num_upstream: int) -> int:
        """Downstream domain size given the upstream domain size."""
        raise NotImplementedError

    def downstream_map(self, upstream_rows: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Given available upstream rows (sorted), return
        (downstream_rows, mapping) where mapping[i] indexes into
        upstream_rows for downstream_rows[i], or -1 for null rows
        (reference get_downstream_rows)."""
        raise NotImplementedError


class AllSampler(DomainSampler):
    name = "All"

    def upstream_rows(self, downstream_rows):
        return np.unique(np.asarray(downstream_rows, np.int64))

    def num_downstream(self, num_upstream):
        return num_upstream

    def downstream_map(self, upstream_rows):
        upstream_rows = np.asarray(upstream_rows, np.int64)
        return upstream_rows.copy(), np.arange(len(upstream_rows))


class StridedSampler(DomainSampler):
    name = "Strided"

    def __init__(self, stride: int):
        if stride <= 0:
            raise GraphException(f"stride must be > 0, got {stride}")
        self.stride = int(stride)

    def upstream_rows(self, downstream_rows):
        return np.unique(np.asarray(downstream_rows, np.int64)) * self.stride

    def num_downstream(self, num_upstream):
        return -(-num_upstream // self.stride)

    def downstream_map(self, upstream_rows):
        upstream_rows = np.asarray(upstream_rows, np.int64)
        hit = upstream_rows % self.stride == 0
        return upstream_rows[hit] // self.stride, np.nonzero(hit)[0]


class StridedRangesSampler(DomainSampler):
    """Concatenation of strided [start, end) ranges."""

    name = "StridedRanges"

    def __init__(self, starts: Sequence[int], ends: Sequence[int],
                 stride: int = 1):
        if len(starts) != len(ends):
            raise GraphException("starts and ends must have the same length")
        if stride <= 0:
            raise GraphException(f"stride must be > 0, got {stride}")
        for s, e in zip(starts, ends):
            if s > e:
                raise GraphException(f"range start {s} after end {e}")
        self.starts = np.asarray(starts, np.int64)
        self.ends = np.asarray(ends, np.int64)
        self.stride = int(stride)
        rows_per = -(-(self.ends - self.starts) // self.stride)
        self.offsets = np.concatenate([[0], np.cumsum(rows_per)])

    def upstream_rows(self, downstream_rows):
        rows = np.unique(np.asarray(downstream_rows, np.int64))
        if len(rows) and (rows[0] < 0 or rows[-1] >= self.offsets[-1]):
            raise GraphException(
                f"row request out of bounds (max {self.offsets[-1] - 1})")
        ri = np.searchsorted(self.offsets, rows, side="right") - 1
        # overlapping ranges can map distinct downstream rows to the same
        # upstream row; keep the sorted-unique contract
        return np.unique(self.starts[ri] + (rows - self.offsets[ri])
                         * self.stride)

    def num_downstream(self, num_upstream):
        # count rows of ranges wholly or partially below num_upstream
        # (reference StridedRangesDomainSampler::get_num_downstream_rows)
        n = 0
        for s, e in zip(self.starts, self.ends):
            if num_upstream >= e:
                n += -(-(e - s) // self.stride)
            else:
                if num_upstream > s:
                    n += -(-(num_upstream - s) // self.stride)
                break
        return int(n)

    def downstream_map(self, upstream_rows):
        upstream_rows = np.asarray(upstream_rows, np.int64)
        down, mapping = [], []
        offset = 0
        range_idx = 0
        for i, r in enumerate(upstream_rows):
            while (range_idx < len(self.ends)
                   and not (self.starts[range_idx] <= r
                            < self.ends[range_idx])):
                offset += -(-(self.ends[range_idx] - self.starts[range_idx])
                            // self.stride)
                range_idx += 1
            if range_idx == len(self.ends):
                break
            rel = r - self.starts[range_idx]
            if rel % self.stride == 0:
                down.append(offset + rel // self.stride)
                mapping.append(i)
        return np.asarray(down, np.int64), np.asarray(mapping, np.int64)


class GatherSampler(DomainSampler):
    name = "Gather"

    def __init__(self, rows: Sequence[int]):
        self.rows = np.asarray(rows, np.int64)

    def upstream_rows(self, downstream_rows):
        rows = np.unique(np.asarray(downstream_rows, np.int64))
        if len(rows) and (rows[0] < 0 or rows[-1] >= len(self.rows)):
            raise GraphException(
                f"gather request out of bounds (max {len(self.rows) - 1})")
        return np.unique(self.rows[rows])

    def num_downstream(self, num_upstream):
        # prefix count up to the first out-of-range row (reference
        # GatherDomainSampler::get_num_downstream_rows breaks at it)
        n = 0
        for r in self.rows:
            if r >= num_upstream:
                break
            n += 1
        return n

    def downstream_map(self, upstream_rows):
        upstream_rows = np.asarray(upstream_rows, np.int64)
        pos = {int(r): i for i, r in enumerate(upstream_rows)}
        down, mapping = [], []
        for d, r in enumerate(self.rows):
            if int(r) in pos:
                down.append(d)
                mapping.append(pos[int(r)])
        return np.asarray(down, np.int64), np.asarray(mapping, np.int64)


class SpaceNullSampler(DomainSampler):
    """Upsample by `spacing`: source row r appears at downstream r*spacing,
    the gap filled with nulls."""

    name = "SpaceNull"

    def __init__(self, spacing: int):
        if spacing <= 0:
            raise GraphException(f"spacing must be > 0, got {spacing}")
        self.spacing = int(spacing)

    def upstream_rows(self, downstream_rows):
        return np.unique(np.asarray(downstream_rows, np.int64) // self.spacing)

    def num_downstream(self, num_upstream):
        return num_upstream * self.spacing

    def downstream_map(self, upstream_rows):
        upstream_rows = np.asarray(upstream_rows, np.int64)
        n = len(upstream_rows)
        down = (upstream_rows[:, None] * self.spacing
                + np.arange(self.spacing)[None, :]).reshape(-1)
        mapping = np.full((n, self.spacing), -1, np.int64)
        mapping[:, 0] = np.arange(n)
        return down, mapping.reshape(-1)


class SpaceRepeatSampler(DomainSampler):
    """Upsample by `spacing`, repeating each source row."""

    name = "SpaceRepeat"

    def __init__(self, spacing: int):
        if spacing <= 0:
            raise GraphException(f"spacing must be > 0, got {spacing}")
        self.spacing = int(spacing)

    def upstream_rows(self, downstream_rows):
        return np.unique(np.asarray(downstream_rows, np.int64) // self.spacing)

    def num_downstream(self, num_upstream):
        return num_upstream * self.spacing

    def downstream_map(self, upstream_rows):
        upstream_rows = np.asarray(upstream_rows, np.int64)
        n = len(upstream_rows)
        down = (upstream_rows[:, None] * self.spacing
                + np.arange(self.spacing)[None, :]).reshape(-1)
        mapping = np.repeat(np.arange(n), self.spacing)
        return down, mapping


_SAMPLERS = {
    "All": lambda args: AllSampler(),
    "Strided": lambda args: StridedSampler(args["stride"]),
    "StridedRanges": lambda args: StridedRangesSampler(
        args["starts"], args["ends"], args.get("stride", 1)),
    "Gather": lambda args: GatherSampler(args["rows"]),
    "SpaceNull": lambda args: SpaceNullSampler(args["spacing"]),
    "SpaceRepeat": lambda args: SpaceRepeatSampler(args["spacing"]),
}


def make_sampler(kind: str, args: Dict) -> DomainSampler:
    if kind not in _SAMPLERS:
        raise GraphException(f"unknown sampler: {kind}")
    return _SAMPLERS[kind](args)


# ---------------------------------------------------------------------------
# Partitioners (slice groups)
# ---------------------------------------------------------------------------

class Partitioner:
    name = "Partitioner"

    def __init__(self, num_rows: int):
        self.num_rows = int(num_rows)

    def total_groups(self) -> int:
        raise NotImplementedError

    def group_at(self, group_idx: int) -> np.ndarray:
        """Upstream rows of group `group_idx`."""
        raise NotImplementedError

    def rows_per_group(self) -> List[int]:
        return [len(self.group_at(g)) for g in range(self.total_groups())]

    def offset_at_group(self, group_idx: int) -> int:
        return int(sum(self.rows_per_group()[:group_idx]))


class StridedPartitioner(Partitioner):
    """Contiguous groups of `group_size` over the (strided) row domain
    (reference StridedPartitioner; `partitioner.all(n)` is stride=1)."""

    name = "Strided"

    def __init__(self, num_rows: int, stride: int = 1, group_size: int = 250):
        super().__init__(num_rows)
        if stride <= 0 or group_size <= 0:
            raise GraphException("stride and group_size must be > 0")
        self.stride = int(stride)
        self.group_size = int(group_size)
        self._strided_rows = -(-self.num_rows // self.stride)

    def total_groups(self):
        return -(-self._strided_rows // self.group_size)

    def group_at(self, group_idx):
        s = self.group_size * group_idx
        e = min(self._strided_rows, s + self.group_size)
        return np.arange(s, e, dtype=np.int64) * self.stride


class StridedRangePartitioner(Partitioner):
    """Each strided [start, end) range is one group (reference
    StridedRangePartitioner; overlapping ranges allowed)."""

    name = "StridedRange"

    def __init__(self, num_rows: int, starts: Sequence[int],
                 ends: Sequence[int], stride: int = 1):
        super().__init__(num_rows)
        if stride <= 0:
            raise GraphException("stride must be > 0")
        if len(starts) != len(ends):
            raise GraphException("starts/ends length mismatch")
        for s, e in zip(starts, ends):
            if s > e:
                raise GraphException(f"range start {s} after end {e}")
            if e > num_rows:
                raise GraphException(
                    f"range end {e} exceeds stream length {num_rows}")
        self.starts = list(starts)
        self.ends = list(ends)
        self.stride = int(stride)

    def total_groups(self):
        return len(self.starts)

    def group_at(self, group_idx):
        return np.arange(self.starts[group_idx], self.ends[group_idx],
                         self.stride, dtype=np.int64)


class GatherPartitioner(Partitioner):
    name = "Gather"

    def __init__(self, num_rows: int, groups: Sequence[Sequence[int]]):
        super().__init__(num_rows)
        self.groups = [np.asarray(g, np.int64) for g in groups]

    def total_groups(self):
        return len(self.groups)

    def group_at(self, group_idx):
        return self.groups[group_idx]


_PARTITIONERS = {
    "Strided": lambda n, args: StridedPartitioner(
        n, args.get("stride", 1), args.get("group_size", 250)),
    "StridedRange": lambda n, args: StridedRangePartitioner(
        n, args["starts"], args["ends"], args.get("stride", 1)),
    "Gather": lambda n, args: GatherPartitioner(n, args["groups"]),
}


def make_partitioner(kind: str, num_rows: int, args: Dict) -> Partitioner:
    if kind not in _PARTITIONERS:
        raise GraphException(f"unknown partitioner: {kind}")
    return _PARTITIONERS[kind](num_rows, args)
