"""Op graph DSL: registries, kernel registration, graph node types.

Capability parity: reference scannerpy/op.py (OpGenerator:121, Op:244,
OpColumn:47, register_python_op:317) + scanner/api/op.h (REGISTER_OP
builder) + the registries in scanner/engine/*_registry.*.

Kernels here are Python classes (usually wrapping a jitted JAX function).
The engine decides host-vs-TPU placement from OpSpec.device.
"""

from __future__ import annotations

import dataclasses
import inspect
import threading
import typing
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

from ..common import (BlobType, DeviceType, FrameType, GraphException,
                      SliceList)

# Builtin op names (reference dag_analysis.h:27-37)
INPUT_OP = "Input"
OUTPUT_OP = "Output"
SAMPLE_OP = "Sample"
SPACE_OP = "Space"
SLICE_OP = "Slice"
UNSLICE_OP = "Unslice"
BUILTIN_OPS = {INPUT_OP, OUTPUT_OP, SAMPLE_OP, SPACE_OP, SLICE_OP, UNSLICE_OP}


class Kernel:
    """Base class for user kernels (reference scannerpy/kernel.py:15 and
    api/kernel.h:145 BaseKernel).

    Lifecycle: __init__(config, **op_args) -> [fetch_resources once per node]
    -> [setup_with_resources] -> per stream: new_stream(**stream_args) ->
    execute(...) repeatedly; reset() on discontinuity (state ops).
    """

    def __init__(self, config: "KernelConfig"):
        self.config = config

    def fetch_resources(self) -> None:
        """Called once per node (not per pipeline instance) before setup."""

    def setup_with_resources(self) -> None:
        """Called after fetch_resources completed on the node."""

    def new_stream(self, **kwargs) -> None:
        """Per-stream (per-job) argument binding."""

    def reset(self) -> None:
        """State reset on row discontinuity (stateful kernels)."""

    def execute(self, *cols, **kwcols):
        raise NotImplementedError

    def execute_traced(self, *cols):
        """Trace-safe core of ``execute()`` for whole-pipeline fusion
        (graph/fusion.py + engine/evaluate.py FusedKernelInstance): the
        engine composes consecutive members' ``execute_traced`` bodies
        into ONE jitted program, so this must accept/return jax arrays
        and stay pure under tracing (no host-side conversion, no
        per-row python results).  The default delegates to
        ``execute()`` — correct for kernels whose execute body is
        already pure jax; kernels with a host-side tail (e.g. a
        float-list conversion) override this with the traced core and
        put the conversion in ``finish()``."""
        return self.execute(*cols)

    def finish(self, result):
        """Host-side tail conversion applied OUTSIDE the fused jit to
        the chain-tail kernel's ``execute_traced`` result, restoring
        the exact ``execute()`` result protocol (identity by
        default)."""
        return result

    def precompile_input(self, name: str):
        """Optional warm-up hook for the engine's bucket-ladder
        precompile (engine/evaluate.py): return one example row for the
        non-frame input column `name` (frame columns are synthesized by
        the engine), or None to opt this op out of generic warm-up.
        The example only needs the right shape/dtype — warm-up results
        are discarded."""
        return None

    def cost(self, shapes):
        """Optional analytical cost descriptor for ONE execute() call
        (the roofline-attribution hook, util/coststats.py): `shapes`
        holds one entry per positional input — the array shape tuple
        for array inputs, the element count for per-row lists.  Return
        a `coststats.CostDescriptor` (or a dict with `flops` /
        `bytes_in` / `bytes_out` keys), or None to fall back to the
        derived default (XLA's cost analysis of the compiled
        executable, else observed argument bytes).  Device kernels in
        the stdlib implement this; scanner-check SC309 enforces it for
        `kernels/` TPU ops."""
        return None

    def close(self) -> None:
        pass


@dataclass
class KernelConfig:
    device: DeviceType
    args: Dict[str, Any] = field(default_factory=dict)
    node_id: int = 0
    # engine-provided: jax devices visible to this kernel instance
    devices: List[Any] = field(default_factory=list)


@dataclass
class OpSpec:
    """Registered op metadata (reference OpInfo/OpRegistry + KernelFactory)."""

    name: str
    input_columns: List[Tuple[str, bool]]   # (name, is_frame)
    output_columns: List[Tuple[str, bool]]
    kernel_factory: Optional[Callable[..., Kernel]] = None
    device: DeviceType = DeviceType.CPU
    stencil: List[int] = field(default_factory=lambda: [0])
    batch: int = 1
    # None = stateless; >=0 = bounded state with that warmup
    bounded_state: Optional[int] = None
    unbounded_state: bool = False
    variadic: bool = False
    # per output column: "frame" | "raw" (bytes) | "pickle" (objects)
    output_codecs: List[str] = field(default_factory=list)
    # names of per-stream (new_stream) parameters
    stream_arg_names: List[str] = field(default_factory=list)
    # names of init (kernel constructor) parameters
    init_arg_names: List[str] = field(default_factory=list)

    @property
    def is_stateful(self) -> bool:
        return self.unbounded_state or self.bounded_state is not None

    def __reduce__(self):
        """Serialize with the kernel class hidden behind a NESTED
        cloudpickle blob, restored through the local registry first
        (`_restore_op_spec`).

        Job specs travel as cloudpickle blobs, and test/user modules
        often ride by value (``register_pickle_by_value``).  Unpickling
        a by-value class in the SAME process is not a no-op even when
        cloudpickle's tracker dedupes it back to the original class
        object: the restore re-applies the pickled class ``__dict__``
        onto the original, silently REBINDING every class attribute to
        a dump-time copy (a mutable registry like ``executed_on = []``
        loses all appends made since the dump — the
        test_distributed_histogram registry-identity flake, where a
        late-joining worker's spec load wiped the list mid-run).
        Nesting the class blob means a process whose registry already
        holds the op NEVER deserializes the class at all — the
        registered spec IS the identity; only a process without the
        registration (a spawned worker that never imported the
        defining module) pays the class unpickle, where there is no
        original to clobber."""
        fields_d = {f.name: getattr(self, f.name)
                    for f in dataclasses.fields(self)
                    if f.name != "kernel_factory"}
        fac = self.kernel_factory
        if fac is None:
            return (_restore_op_spec, (fields_d, None, None))
        identity = (getattr(fac, "__module__", None),
                    getattr(fac, "__qualname__", None))
        # reentrancy guard: the class's own dump reaches its `_op_spec`
        # backref and would recurse dumps(class) forever; the nested
        # copy travels factory-less (the outer spec carries the blob)
        active = getattr(_SPEC_REDUCE_GUARD, "active", None)
        if active is None:
            active = _SPEC_REDUCE_GUARD.active = set()
        if id(fac) in active:
            return (_restore_op_spec, (fields_d, identity, None))
        active.add(id(fac))
        try:
            import cloudpickle
            blob = cloudpickle.dumps(fac)
        finally:
            active.discard(id(fac))
        return (_restore_op_spec, (fields_d, identity, blob))


_SPEC_REDUCE_GUARD = threading.local()


def _restore_op_spec(fields_d: Dict[str, Any],
                     identity: Optional[Tuple],
                     blob: Optional[bytes]) -> "OpSpec":
    """Unpickle-side twin of OpSpec.__reduce__: when the local registry
    holds a same-named op whose class matches the dump-time identity
    (module + qualname), the REGISTERED spec is returned verbatim —
    one canonical identity per process, zero class deserialization.
    Otherwise the embedded class blob is loaded (spawned workers)."""
    name = fields_d.get("name")
    if identity is not None and name is not None and registry.has(name):
        local = registry.get(name)
        lf = local.kernel_factory
        if lf is not None and (getattr(lf, "__module__", None),
                               getattr(lf, "__qualname__", None)) \
                == tuple(identity):
            return local
    factory = None
    if blob is not None:
        import cloudpickle
        factory = cloudpickle.loads(blob)
    return OpSpec(kernel_factory=factory, **fields_d)


class OpRegistry:
    def __init__(self):
        self._ops: Dict[str, OpSpec] = {}

    def register(self, spec: OpSpec) -> None:
        if spec.name in BUILTIN_OPS:
            raise GraphException(f"cannot register builtin name {spec.name}")
        self._ops[spec.name] = spec

    def get(self, name: str) -> OpSpec:
        if name not in self._ops:
            raise GraphException(
                f"op not registered: {name} (have: {sorted(self._ops)})")
        return self._ops[name]

    def has(self, name: str) -> bool:
        return name in self._ops

    def canonical_factory(self, spec: OpSpec) -> Optional[Callable]:
        """Resolve a spec's kernel factory to ONE canonical class.

        Job specs travel as cloudpickle blobs; with
        ``register_pickle_by_value`` the kernel class rides by value,
        and the unpickled spec can carry a *class copy* distinct from
        the locally-registered original (cloudpickle's class tracker
        is best-effort).  In-process clusters then split identity:
        kernels execute on the copy while everything that looked the
        class up by name (tests, class-level state, re-registration)
        holds the original.  When the local registry has a same-named
        op whose class is the same module+qualname, the registered
        class IS the op — return it; otherwise (spawned workers that
        never imported the defining module, genuinely different ops)
        the spec's own factory stands."""
        fac = spec.kernel_factory
        local = self._ops.get(spec.name)
        if fac is None or local is None or local.kernel_factory is None:
            return fac
        lf = local.kernel_factory
        if lf is fac:
            return fac
        if (getattr(lf, "__module__", None)
                == getattr(fac, "__module__", None)
                and getattr(lf, "__qualname__", None)
                == getattr(fac, "__qualname__", None)):
            return lf
        return fac

    def names(self) -> List[str]:
        return sorted(self._ops)


registry = OpRegistry()


def _is_frame_ann(ann) -> bool:
    return ann is FrameType


def _strip_seq(ann) -> Tuple[Any, int]:
    """Unwrap Sequence[...] layers; returns (inner, depth)."""
    depth = 0
    while typing.get_origin(ann) in (list, tuple, typing.Sequence,
                                     typing.get_origin(Sequence[int])):
        args = typing.get_args(ann)
        if not args:
            break
        ann = args[0]
        depth += 1
    return ann, depth


def register_op(name: Optional[str] = None,
                device: DeviceType = DeviceType.CPU,
                batch: int = 1,
                stencil: Optional[List[int]] = None,
                bounded_state: Optional[int] = None,
                unbounded_state: bool = False):
    """Decorator registering a Kernel class or a plain function as an op.

    Input/output columns are inferred from the `execute` type annotations
    (reference register_python_op, op.py:317-575): FrameType = video frames,
    anything else = serialized blob.  Sequence[...] wrapping indicates
    batch and/or stencil axes and is validated against the decl.
    """

    def wrap(target):
        op_name = name or target.__name__
        if inspect.isclass(target) and issubclass(target, Kernel):
            cls = target
            exec_fn = target.execute
            skip_self = 1
        elif callable(target):
            # plain function kernel: def f(config, col: T, ...) -> Out
            fn = target

            class FnKernel(Kernel):
                def __init__(self, config, **kw):
                    super().__init__(config)
                    self._kw = kw

                def execute(self, *cols):
                    return fn(self.config, *cols, **self._kw)

            FnKernel.__name__ = op_name
            cls = FnKernel
            exec_fn = fn
            skip_self = 1  # `config` occupies the first slot
        else:
            raise GraphException(f"cannot register {target!r} as op")

        # eval_str resolves PEP-563 string annotations (modules using
        # `from __future__ import annotations`)
        sig = inspect.signature(exec_fn, eval_str=True)
        params = list(sig.parameters.values())[skip_self:]
        in_cols: List[Tuple[str, bool]] = []
        variadic = False
        init_args: List[str] = []
        for p in params:
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                inner, _ = _strip_seq(p.annotation)
                in_cols.append((p.name, _is_frame_ann(inner)))
                variadic = True
            elif p.annotation is not inspect.Parameter.empty:
                inner, _ = _strip_seq(p.annotation)
                in_cols.append((p.name, _is_frame_ann(inner)))
            else:
                init_args.append(p.name)
        def codec_of(inner) -> str:
            if _is_frame_ann(inner):
                return "frame"
            if inner is bytes:
                return "raw"
            return "pickle"

        ret = sig.return_annotation
        out_cols: List[Tuple[str, bool]] = []
        out_codecs: List[str] = []
        if ret is inspect.Signature.empty or ret is None:
            out_cols = [("output", False)]
            out_codecs = ["pickle"]
        elif typing.get_origin(ret) is tuple:
            for i, r in enumerate(typing.get_args(ret)):
                inner, _ = _strip_seq(r)
                out_cols.append((f"output{i}", _is_frame_ann(inner)))
                out_codecs.append(codec_of(inner))
        else:
            inner, _ = _strip_seq(ret)
            out_cols = [("output", _is_frame_ann(inner))]
            out_codecs = [codec_of(inner)]

        # new_stream kwargs (per-stream args)
        stream_args: List[str] = []
        ns = getattr(cls, "new_stream", None)
        if ns is not None and ns is not Kernel.new_stream:
            stream_args = [p.name for p in
                           list(inspect.signature(ns).parameters.values())[1:]
                           if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                                         inspect.Parameter.KEYWORD_ONLY)]
        # constructor kwargs beyond config
        if inspect.isclass(target):
            ctor = inspect.signature(cls.__init__)
            init_args = [p.name for p in
                         list(ctor.parameters.values())[2:]
                         if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                                       inspect.Parameter.KEYWORD_ONLY)]

        spec = OpSpec(
            name=op_name, input_columns=in_cols, output_columns=out_cols,
            kernel_factory=cls, device=device,
            stencil=list(stencil) if stencil else [0], batch=batch,
            bounded_state=bounded_state, unbounded_state=unbounded_state,
            variadic=variadic, output_codecs=out_codecs,
            stream_arg_names=stream_args, init_arg_names=init_args)
        registry.register(spec)
        target._op_spec = spec
        return target

    return wrap


# ---------------------------------------------------------------------------
# Graph node types
# ---------------------------------------------------------------------------

class OpColumn:
    """A named output stream of a graph node (reference op.py:47)."""

    def __init__(self, op: "OpNode", column: str, is_frame: bool):
        self.op = op
        self.column = column
        self.is_frame = is_frame
        # output-encoding options (reference OpColumn.compress/lossless)
        self.encode_options: Dict[str, Any] = {}

    def lossless(self) -> "OpColumn":
        c = OpColumn(self.op, self.column, self.is_frame)
        c.encode_options = {"codec": "video", "crf": 0}
        return c

    def compress(self, codec: str = "video", bitrate: int = 0,
                 crf: int = 20, keyint: int = 16) -> "OpColumn":
        c = OpColumn(self.op, self.column, self.is_frame)
        c.encode_options = {"codec": codec, "bitrate": bitrate, "crf": crf,
                            "keyint": keyint}
        return c

    def __repr__(self):
        return f"OpColumn({self.op.name}.{self.column})"


class OpNode:
    """One node of the computation graph."""

    _counter = [0]

    def __init__(self, name: str,
                 inputs: Dict[str, Union[OpColumn, List[OpColumn]]],
                 job_args: Optional[Dict[str, List[Any]]] = None,
                 device: Optional[DeviceType] = None,
                 stencil: Optional[List[int]] = None,
                 batch: Optional[int] = None,
                 warmup: Optional[int] = None,
                 extra: Optional[Dict[str, Any]] = None,
                 init_args: Optional[Dict[str, Any]] = None,
                 fuse: Optional[bool] = None):
        self.name = name
        self.inputs = inputs
        self.job_args = job_args or {}     # per-stream op args (length = #jobs)
        self.init_args = init_args or {}   # kernel constructor args
        self.device = device
        self.stencil = stencil
        self.batch = batch
        self.warmup = warmup
        # whole-pipeline fusion override (graph/fusion.py): False pins
        # this node to staged dispatch (a chain boundary); None/True
        # leave the planner's eligibility + cost decision in charge
        self.fuse = fuse
        self.extra = extra or {}           # builtin payload (sampler kind etc.)
        self.id = OpNode._counter[0]
        OpNode._counter[0] += 1

        if name in BUILTIN_OPS:
            self.spec: Optional[OpSpec] = None
            out_is_frame = self._builtin_output_is_frame()
            self.outputs = [OpColumn(self, "output", out_is_frame)]
        else:
            self.spec = registry.get(name)
            self.outputs = [OpColumn(self, cname, isf)
                            for cname, isf in self.spec.output_columns]

    def _builtin_output_is_frame(self) -> bool:
        for v in self.inputs.values():
            cols = v if isinstance(v, list) else [v]
            for c in cols:
                return c.is_frame
        return True  # Input op: frames by default; set explicitly by caller

    @property
    def is_builtin(self) -> bool:
        return self.name in BUILTIN_OPS

    def input_columns(self) -> List[OpColumn]:
        out: List[OpColumn] = []
        for v in self.inputs.values():
            if isinstance(v, list):
                out.extend(v)
            else:
                out.append(v)
        return out

    def effective_stencil(self) -> List[int]:
        if self.stencil is not None:
            return list(self.stencil)
        if self.spec is not None:
            return list(self.spec.stencil)
        return [0]

    def effective_batch(self) -> int:
        if self.batch is not None:
            return int(self.batch)
        if self.spec is not None:
            return int(self.spec.batch)
        return 1

    def effective_device(self) -> DeviceType:
        if self.device is not None:
            return self.device
        if self.spec is not None:
            return self.spec.device
        return DeviceType.CPU

    def __getitem__(self, column: str) -> OpColumn:
        for c in self.outputs:
            if c.column == column:
                return c
        raise GraphException(f"op {self.name} has no output column {column}")

    def __repr__(self):
        return f"OpNode({self.name}#{self.id})"


class OpGenerator:
    """`ops.Name(col=..., arg=...)` dynamic op construction
    (reference OpGenerator, op.py:121-133)."""

    def __getattr__(self, name: str):
        def make(*args, **kwargs) -> OpColumn:
            spec = registry.get(name)
            device = kwargs.pop("device", None)
            stencil = kwargs.pop("stencil", None)
            batch = kwargs.pop("batch", None)
            warmup = kwargs.pop("bounded_state", None)
            fuse = kwargs.pop("fuse", None)
            inputs: Dict[str, Union[OpColumn, List[OpColumn]]] = {}
            job_args: Dict[str, List[Any]] = {}
            init_args: Dict[str, Any] = {}
            if spec.variadic:
                if kwargs.get(spec.input_columns[0][0]) is not None:
                    cols = kwargs.pop(spec.input_columns[0][0])
                else:
                    cols = list(args)
                if not all(isinstance(c, OpColumn) for c in cols):
                    raise GraphException(
                        f"{name}: variadic inputs must be OpColumns")
                inputs[spec.input_columns[0][0]] = list(cols)
            else:
                in_names = {n for n, _ in spec.input_columns}
                for n, _ in spec.input_columns:
                    if n in kwargs:
                        v = kwargs.pop(n)
                        if not isinstance(v, OpColumn):
                            raise GraphException(
                                f"{name}: input {n} must be an OpColumn")
                        inputs[n] = v
                if len(inputs) != len(in_names):
                    missing = in_names - set(inputs)
                    raise GraphException(f"{name}: missing inputs {missing}")
            # remaining kwargs: per-stream args (lists) or init args
            for k, v in kwargs.items():
                if k in spec.stream_arg_names:
                    if not isinstance(v, (list, SliceList)):
                        raise GraphException(
                            f"{name}: per-stream arg {k} must be a list "
                            f"(one entry per input stream)")
                    job_args[k] = v
                else:
                    init_args[k] = v
            node = OpNode(name, inputs, job_args=job_args, device=device,
                          stencil=stencil, batch=batch, warmup=warmup,
                          init_args=init_args, fuse=fuse)
            if len(node.outputs) == 1:
                return node.outputs[0]
            return node  # caller selects columns via node['col']

        return make
