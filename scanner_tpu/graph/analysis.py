"""DAG analysis: validation, row-domain sizing, task generation, and
backward row-requirement derivation.

Capability parity: reference scanner/engine/dag_analysis.{h,cpp} —
validate_jobs_and_ops (:43), populate_analysis_info (:898),
perform_liveness_analysis (:1145), derive_stencil_requirements (:1328-1746).

The computation graph is a DAG of OpNodes.  For each job (input-stream
binding) the analysis:
  1. validates the graph (slice-level agreement, IO placement, equal-length
     zips),
  2. sizes every op's row domain per slice group (forward pass),
  3. chunks the output domain into tasks aligned to slice-group boundaries,
  4. for one task, walks the DAG backwards deriving, per op, exactly which
     input rows are needed (through samplers, stencils, warmup, slices) —
     producing the TaskStreams the evaluate stage executes and the minimal
     row set the source must load/decode.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import GraphException, SliceList
from . import ops as O
from . import samplers as S


# ---------------------------------------------------------------------------
# Graph structure analysis (job-independent)
# ---------------------------------------------------------------------------

@dataclass
class GraphInfo:
    ops: List[O.OpNode]                       # topological order
    op_index: Dict[int, int]                  # node id -> position
    consumers: Dict[int, List[int]]           # node id -> consumer node ids
    slice_level: Dict[int, int]               # node id -> slice depth
    sources: List[O.OpNode]
    sinks: List[O.OpNode]
    num_jobs: int

    def op_at(self, node_id: int) -> O.OpNode:
        return self.ops[self.op_index[node_id]]


def analyze(outputs: Sequence[O.OpNode]) -> GraphInfo:
    """Validate and linearize the graph reachable from the given sinks."""
    sinks = list(outputs)
    for s in sinks:
        if s.name != O.OUTPUT_OP:
            raise GraphException("run() targets must be io.Output ops")

    # toposort (reference client.py:448 _toposort)
    order: List[O.OpNode] = []
    state: Dict[int, int] = {}

    def visit(n: O.OpNode):
        st = state.get(n.id, 0)
        if st == 1:
            raise GraphException("graph contains a cycle")
        if st == 2:
            return
        state[n.id] = 1
        for c in n.input_columns():
            visit(c.op)
        state[n.id] = 2
        order.append(n)

    for s in sinks:
        visit(s)

    consumers: Dict[int, List[int]] = {n.id: [] for n in order}
    for n in order:
        for c in n.input_columns():
            consumers[c.op.id].append(n.id)

    sources = [n for n in order if n.name == O.INPUT_OP]
    if not sources:
        raise GraphException("graph has no io.Input source")
    # IO only at graph edges (reference dag_analysis remap invariants)
    for n in order:
        if n.name == O.OUTPUT_OP and consumers[n.id]:
            raise GraphException("io.Output cannot feed other ops")
        if n.name == O.INPUT_OP and n.input_columns():
            raise GraphException("io.Input takes no graph inputs")

    # only one slice/unslice pair per pipeline (reference
    # evaluate_worker.cpp:844-847 "we guarantee only one slice per pipeline")
    n_slices = sum(1 for n in order if n.name == O.SLICE_OP)
    n_unslices = sum(1 for n in order if n.name == O.UNSLICE_OP)
    if n_slices > 1 or n_unslices > 1:
        raise GraphException("only one Slice/Unslice pair per graph")

    # slice levels (reference: single slice level, no nesting,
    # dag_analysis.cpp:70-154)
    level: Dict[int, int] = {}
    for n in order:
        in_levels = {level[c.op.id] for c in n.input_columns()}
        if len(in_levels) > 1:
            raise GraphException(
                f"op {n.name}: inputs at differing slice levels {in_levels}")
        base = in_levels.pop() if in_levels else 0
        if n.name == O.SLICE_OP:
            if base != 0:
                raise GraphException("nested slices are not supported")
            level[n.id] = 1
        elif n.name == O.UNSLICE_OP:
            if base != 1:
                raise GraphException("unslice without matching slice")
            level[n.id] = 0
        else:
            level[n.id] = base
    for s in sinks:
        if level[s.id] != 0:
            raise GraphException(
                "sliced streams must be unsliced before io.Output")
    # unslice outputs may only feed sinks (reference evaluate_worker
    # guarantee, dag_analysis.cpp:151-153)
    for n in order:
        if n.name == O.UNSLICE_OP:
            for cid in consumers[n.id]:
                cons = next(x for x in order if x.id == cid)
                if cons.name not in (O.OUTPUT_OP,):
                    raise GraphException(
                        "unslice output may only feed io.Output")

    # number of jobs: every per-stream binding must agree
    njobs: Optional[int] = None

    def check_n(n_streams: int, what: str):
        nonlocal njobs
        if njobs is None:
            njobs = n_streams
        elif njobs != n_streams:
            raise GraphException(
                f"{what} binds {n_streams} streams but job count is {njobs}")

    for n in order:
        if n.name == O.INPUT_OP:
            check_n(len(n.extra["streams"]), "io.Input")
        elif n.name == O.OUTPUT_OP:
            check_n(len(n.extra["streams"]), "io.Output")
        if n.extra.get("args_per_stream") is not None:
            check_n(len(n.extra["args_per_stream"]), f"{n.name} args")
        for k, v in n.job_args.items():
            check_n(len(v), f"{n.name}.{k}")
    assert njobs is not None

    return GraphInfo(ops=order,
                     op_index={n.id: i for i, n in enumerate(order)},
                     consumers=consumers, slice_level=level,
                     sources=sources, sinks=sinks, num_jobs=njobs)


# ---------------------------------------------------------------------------
# Per-job row sizing (forward pass)
# ---------------------------------------------------------------------------

@dataclass
class JobRows:
    job_idx: int
    # node id -> rows per slice group (level 0 => single entry)
    rows: Dict[int, List[int]]
    # node id -> sampler per group (Sample/Space ops)
    samplers: Dict[int, List[S.DomainSampler]]
    # node id -> partitioner (Slice ops)
    partitioners: Dict[int, S.Partitioner]
    num_groups: int  # 1 if no slicing
    # output row count (all sinks validated equal)
    output_rows: int
    # output-domain slice-group boundaries (cumulative ends); [output_rows]
    # when no slicing
    group_ends: List[int]
    # rows per compute batch pushed to a batch-capable kernel (the XLA
    # batch dimension) — resolved from PerfParams.work_packet_size at job
    # preparation (reference io/work packet split, master.cpp:1421)
    work_packet_size: int = 16


def _sampler_args_for(node: O.OpNode, job_idx: int):
    args = node.extra.get("args_per_stream")
    if args is None:
        # argless samplers (All) apply identically to every stream
        return {}
    return args[job_idx]


def job_rows(info: GraphInfo, job_idx: int,
             source_rows: Dict[int, int]) -> JobRows:
    """Forward-size every op's row domain for one job.

    source_rows: node id of each Input op -> stream length.
    """
    rows: Dict[int, List[int]] = {}
    samplers: Dict[int, List[S.DomainSampler]] = {}
    partitioners: Dict[int, S.Partitioner] = {}
    num_groups = 1

    for n in info.ops:
        if n.name == O.INPUT_OP:
            rows[n.id] = [source_rows[n.id]]
        elif n.name in (O.SAMPLE_OP, O.SPACE_OP):
            inp = n.input_columns()[0].op
            kind = n.extra["sampler_kind"]
            args = _sampler_args_for(n, job_idx)
            per_group: List[S.DomainSampler] = []
            in_rows = rows[inp.id]
            if isinstance(args, SliceList):
                if info.slice_level[n.id] == 0:
                    raise GraphException(
                        f"{n.name}: SliceList args outside a slice")
                if len(args) != len(in_rows):
                    raise GraphException(
                        f"{n.name}: SliceList has {len(args)} entries for "
                        f"{len(in_rows)} slice groups")
                for a in args:
                    per_group.append(S.make_sampler(kind, a))
            else:
                per_group = [S.make_sampler(kind, args)] * len(in_rows)
            samplers[n.id] = per_group
            rows[n.id] = [per_group[g].num_downstream(in_rows[g])
                          for g in range(len(in_rows))]
        elif n.name == O.SLICE_OP:
            inp = n.input_columns()[0].op
            kind = n.extra["partitioner_kind"]
            args = _sampler_args_for(n, job_idx)
            part = S.make_partitioner(kind, rows[inp.id][0], args)
            partitioners[n.id] = part
            rows[n.id] = part.rows_per_group()
            num_groups = part.total_groups()
        elif n.name == O.UNSLICE_OP:
            inp = n.input_columns()[0].op
            rows[n.id] = [int(sum(rows[inp.id]))]
        else:
            in_cols = n.input_columns()
            first = rows[in_cols[0].op.id]
            for c in in_cols[1:]:
                if rows[c.op.id] != first:
                    raise GraphException(
                        f"op {n.name}: input row domains differ "
                        f"({rows[c.op.id]} vs {first}); all zipped inputs "
                        f"must have equal lengths")
            rows[n.id] = list(first)

    out_counts = {rows[s.input_columns()[0].op.id][0] for s in info.sinks}
    if len(out_counts) != 1:
        raise GraphException(
            f"all outputs must have the same number of rows, got "
            f"{sorted(out_counts)}")
    output_rows = out_counts.pop()

    # output-domain group boundaries: from the unslice feeding the sink
    # chain if any slicing happened
    group_ends = [output_rows]
    for n in info.ops:
        if n.name == O.UNSLICE_OP:
            inp = n.input_columns()[0].op
            group_ends = list(np.cumsum(rows[inp.id]).astype(int))
            break

    return JobRows(job_idx=job_idx, rows=rows, samplers=samplers,
                   partitioners=partitioners, num_groups=num_groups,
                   output_rows=output_rows, group_ends=group_ends)


# ---------------------------------------------------------------------------
# Task generation (reference master.cpp:1558-1607)
# ---------------------------------------------------------------------------

def generate_tasks(jr: JobRows, io_packet_size: int) -> List[Tuple[int, int]]:
    """Chunk the output domain into [start, end) tasks of at most
    io_packet_size rows, never crossing a slice-group boundary."""
    if io_packet_size <= 0:
        raise GraphException(
            f"io_packet_size must be > 0, got {io_packet_size}")
    tasks: List[Tuple[int, int]] = []
    start = 0
    for end in jr.group_ends:
        s = start
        while s < end:
            e = min(s + io_packet_size, end)
            tasks.append((s, e))
            s = e
        start = end
    return tasks


# ---------------------------------------------------------------------------
# Backward derivation (reference derive_stencil_requirements,
# dag_analysis.cpp:1328-1746)
# ---------------------------------------------------------------------------

@dataclass
class TaskStream:
    """Per-op row bookkeeping for one task (reference runtime.h:69)."""
    node_id: int
    slice_group: int
    valid_input_rows: np.ndarray    # rows of the op's input domain it receives
    compute_rows: np.ndarray        # rows it must execute (incl. warmup)
    valid_output_rows: np.ndarray   # rows it must hand downstream


@dataclass
class TaskPlan:
    job_idx: int
    task_idx: int
    output_range: Tuple[int, int]
    streams: Dict[int, TaskStream]          # node id -> stream
    # Input node id -> rows of the stored stream to load/decode
    source_rows: Dict[int, np.ndarray]
    slice_group: int
    # (unbounded-state node id, slice group) -> last compute row this
    # plan advances the kernel through; the NEXT task of an affinity
    # chain may start its recompute after this watermark
    carry_watermarks: Dict[Tuple[int, int], int] = field(
        default_factory=dict)


def derive_task_streams(info: GraphInfo, jr: JobRows,
                        output_range: Tuple[int, int],
                        job_idx: int = 0, task_idx: int = 0,
                        carry: Optional[Dict[Tuple[int, int], int]] = None
                        ) -> TaskPlan:
    out_rows = np.arange(output_range[0], output_range[1], dtype=np.int64)

    required_out: Dict[int, set] = {n.id: set() for n in info.ops}
    for s in info.sinks:
        required_out[s.id].update(out_rows.tolist())

    streams: Dict[int, TaskStream] = {}
    source_rows: Dict[int, np.ndarray] = {}
    watermarks: Dict[Tuple[int, int], int] = {}
    slice_group = 0

    for n in reversed(info.ops):
        downstream = np.asarray(sorted(required_out[n.id]), np.int64)
        compute = None

        if n.name == O.INPUT_OP:
            new_rows = downstream
            source_rows[n.id] = new_rows
        elif n.name in (O.SAMPLE_OP, O.SPACE_OP):
            g = slice_group if info.slice_level[n.id] > 0 else 0
            new_rows = jr.samplers[n.id][g].upstream_rows(downstream)
        elif n.name == O.SLICE_OP:
            # rows are group-local below the slice; remap into the global
            # input domain (task never crosses groups)
            group = jr.partitioners[n.id].group_at(slice_group)
            new_rows = group[downstream]
        elif n.name == O.UNSLICE_OP:
            # locate the single group containing this task's rows
            inp = n.input_columns()[0].op
            counts = jr.rows[inp.id]
            offsets = np.concatenate([[0], np.cumsum(counts)])
            lo, hi = int(downstream[0]), int(downstream[-1])
            g = int(np.searchsorted(offsets, lo, side="right")) - 1
            if g < 0 or hi >= offsets[g + 1]:
                raise GraphException(
                    f"task rows {lo}..{hi} cross slice-group boundaries "
                    f"{list(offsets)}")
            slice_group = g
            new_rows = downstream - offsets[g]
        elif n.name == O.OUTPUT_OP:
            new_rows = downstream
        else:
            # regular op: state warmup, then stencil dilation, then clamp
            cur = set(downstream.tolist())
            if n.spec is not None and n.spec.unbounded_state:
                # Unbounded state means EVERY task recomputes rows 0..end
                # so tasks stay self-contained and reassignable (the
                # reference instead pins a task's packets to one worker,
                # save_coordinator worker.cpp:373-415).  Total work is
                # O(stream_len^2 / io_packet) — UNLESS the caller opts
                # into stateful task affinity (PerfParams
                # .stateful_task_affinity), where `carry` names the row
                # each kernel's state already advanced through in this
                # (job, slice group): the task then recomputes only the
                # rows past the watermark, O(n) total.  The evaluator
                # verifies the premise at run time (KernelInstance
                # watermark) and falls back to the self-contained plan on
                # any mismatch, so correctness never rests on the carry.
                # Long un-sliced streams WITHOUT affinity should Slice()
                # (per-group state reset bounds the recompute span) or
                # declare bounded_state.
                g = slice_group if info.slice_level[n.id] > 0 else 0
                lo = 0
                if carry is not None:
                    mark = carry.get((n.id, g))
                    # carry only when every needed output is past the
                    # watermark — an already-consumed output row cannot
                    # be re-emitted by a stateful kernel
                    if mark is not None and len(downstream) \
                            and int(downstream[0]) > mark:
                        lo = mark + 1
                cur = set(range(lo, int(downstream[-1]) + 1)) \
                    if len(downstream) else set()
                if len(downstream):
                    watermarks[(n.id, g)] = int(downstream[-1])
            elif ((n.spec is not None and n.spec.bounded_state is not None)
                  or n.warmup is not None):
                warmup = n.warmup if n.warmup is not None \
                    else n.spec.bounded_state
                for r in downstream.tolist():
                    for i in range(warmup + 1):
                        if r - i >= 0:
                            cur.add(r - i)
            compute = np.asarray(sorted(cur), np.int64)
            stencil = n.effective_stencil()
            sten = set()
            for r in cur:
                for s_off in stencil:
                    sten.add(r + s_off)
            g = slice_group if info.slice_level[n.id] > 0 else 0
            in_op = n.input_columns()[0].op
            max_rows = jr.rows[in_op.id][g]
            new_rows = np.asarray(
                sorted(r for r in sten if 0 <= r < max_rows), np.int64)

        if not n.name == O.INPUT_OP:
            for c in n.input_columns():
                required_out[c.op.id].update(new_rows.tolist())

        if compute is None:
            compute = new_rows

        streams[n.id] = TaskStream(
            node_id=n.id, slice_group=slice_group,
            valid_input_rows=new_rows, compute_rows=compute,
            valid_output_rows=downstream)

    # nodes visited before the Unslice (the sinks) were stamped with the
    # initial slice_group; a task is always within one group, so backfill
    for ts in streams.values():
        ts.slice_group = slice_group

    # (sliced nodes sit upstream of their Unslice, so the reversed walk
    # fixes slice_group before visiting them — watermark keys are final)
    return TaskPlan(job_idx=job_idx, task_idx=task_idx,
                    output_range=output_range, streams=streams,
                    source_rows=source_rows, slice_group=slice_group,
                    carry_watermarks=watermarks)
