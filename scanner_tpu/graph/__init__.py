from . import analysis, ops, samplers
from .ops import (Kernel, KernelConfig, OpColumn, OpGenerator, OpNode,
                  OpSpec, register_op, registry)
from .streams_dsl import IOGenerator, StreamsGenerator, TaskPartitioner

__all__ = [
    "analysis", "ops", "samplers", "Kernel", "KernelConfig", "OpColumn",
    "OpGenerator", "OpNode", "OpSpec", "register_op", "registry",
    "IOGenerator", "StreamsGenerator", "TaskPartitioner",
]
