"""Single-node pipelined job executor.

Capability parity: reference worker-side pipeline (worker.cpp:1467-1724
thread spawn; load_worker/evaluate_worker/save_worker stage drivers) minus
the RPC shell, which engine/service.py adds for the distributed path.

Stages, connected by bounded queues (reference runtime.h:81-90):

    task list -> [loader xL] -> [evaluator xP] -> [saver xS] -> commit

Loaders read item bytes / decode exact frame sets (C++ releases the GIL, so
loader threads overlap evaluator Python/JAX time).  Each evaluator thread is
one pipeline instance owning its kernel set.  Savers H.264-encode video
outputs and write column items.  Tasks are self-contained (warmup rows are
re-derived per task), so any instance may take any task.
"""

from __future__ import annotations

import queue
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import (CacheMode, DeviceType, JobException, NullElement,
                      PerfParams, ScannerException)
from ..graph import analysis as A
from ..graph import ops as O
from ..storage import Database
from ..storage import items as IT
from ..storage import metadata as md
from ..storage.streams import NamedVideoStream, StoredStream
from ..util import faults as _faults
from ..util import metrics as _mx
from ..util import tracing as _tr
from ..util.log import get_logger
from ..util.profiler import Profiler
from . import framecache as _fc
from .batch import ColumnBatch, concat_batches
from .evaluate import TaskEvaluator

# live pipeline telemetry (docs/observability.md).  Queue depths answer
# the round-3 attribution question ("which stage starves?") in real
# time: a full evaluate queue + idle save queue = compute-bound, etc.
_M_QDEPTH = _mx.registry().gauge(
    "scanner_tpu_stage_queue_depth",
    "Tasks currently queued ahead of a pipeline stage (live; sampled "
    "at scrape time from the bounded inter-stage queues).",
    labels=["stage"])
_M_STAGE_SECONDS = _mx.registry().counter(
    "scanner_tpu_stage_seconds_total",
    "Wall seconds spent in each pipeline stage across all stage threads.",
    labels=["stage"])
_M_STAGE_TASKS = _mx.registry().counter(
    "scanner_tpu_stage_tasks_total",
    "Tasks completed per pipeline stage.",
    labels=["stage"])
_M_CHUNK_WAIT = _mx.registry().counter(
    "scanner_tpu_chunk_wait_seconds_total",
    "Evaluator seconds spent waiting on loader chunk production "
    "(work-packet streaming starvation; mirrors evaluate:chunk_wait "
    "trace intervals).")
_M_DECODED = _mx.registry().counter(
    "scanner_tpu_decoded_frames_total",
    "Video frames decoded and delivered to the pipeline, per loader "
    "thread.",
    labels=["loader"])
_M_DECODE_SECONDS = _mx.registry().counter(
    "scanner_tpu_decode_seconds_total",
    "Seconds spent decoding video frames, per loader thread.",
    labels=["loader"])
# per-chip utilization under evaluator affinity: every chip of a
# multi-device host should take tasks and accumulate busy seconds; a
# chip stuck at zero while siblings climb = an instance wedged or an
# assignment bug ("default" = affinity off / single device)
_M_DEV_TASKS = _mx.registry().counter(
    "scanner_tpu_device_tasks_total",
    "Tasks evaluated per assigned device (pipeline-instance affinity: "
    "instance i stages and runs on chip i mod n_devices).",
    labels=["device"])
_M_DEV_BUSY = _mx.registry().counter(
    "scanner_tpu_device_busy_seconds_total",
    "Evaluate-stage wall seconds per assigned device — the per-chip "
    "utilization series (busy/elapsed per chip ~ affinity efficiency).",
    labels=["device"])
# end-to-end per-task latency: enqueue (task runnable — local admission
# or master bulk admission) to sink-committed.  The seed for
# serving-mode p50/p99 (ROADMAP item 2): under a request-shaped
# workload each "task" is a request and this histogram IS the latency
# SLO series.  Observed by the committing side only — the local saver,
# or the master at FinishedWork — so cluster runs never double-count.
_M_TASK_LATENCY = _mx.registry().histogram(
    "scanner_tpu_task_latency_seconds",
    "End-to-end per-task latency from enqueue to sink-committed "
    "(local: admission to save completion; cluster: bulk admission to "
    "FinishedWork, observed on the master).",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0, 300.0, 600.0))

_SENTINEL = object()
_CHUNK_DONE = object()   # streaming producer: all chunks delivered
_CHUNK_ERR = object()    # streaming producer: (marker, exception)

_log = get_logger("engine")


@dataclass
class JobContext:
    job_idx: int
    jr: A.JobRows
    tasks: List[Tuple[int, int]]
    # per Input node: metadata for loading
    source_info: Dict[int, Dict[str, Any]]
    # per sink node id: (table descriptor, column name, codec, encode opts)
    sink_tables: Dict[int, Tuple[md.TableDescriptor, str, str, Dict]]
    fps: float = 30.0
    skipped: bool = False
    tasks_done: int = 0
    # sparse-read crossover for column loads (PerfParams
    # .load_sparsity_threshold -> items.read_item_rows)
    sparsity_threshold: int = 8
    # per sink id: "video" | "pickle", fixed by the first task written so
    # mixed-dtype frame outputs fail loudly instead of corrupting the table
    sink_modes: Dict[int, str] = field(default_factory=dict)
    sink_mode_lock: threading.Lock = field(default_factory=threading.Lock)
    # sinks writing through a CustomStorage instead of the database
    custom_sinks: Dict[int, Any] = field(default_factory=dict)


@dataclass
class TaskItem:
    job: JobContext
    task_idx: int
    output_range: Tuple[int, int]
    plan: Optional[A.TaskPlan] = None
    elements: Optional[Dict[int, Any]] = None
    results: Optional[Dict[int, Any]] = None
    # master-assigned attempt id (cluster mode): distinguishes re-issues
    # of the same task after a timeout revocation
    attempt: int = 0
    # distributed tracing (util/tracing.py): the parent context this
    # task's span attaches under (local: the job root span; cluster: the
    # master's assign span from the NextWork reply), and the open task
    # span itself — created by the loader, resumed by each stage thread,
    # closed after save/failure
    trace_ctx: Optional[Any] = None
    trace_span: Optional[Any] = None
    # when this task became runnable; 0 = unknown (cluster workers leave
    # it unset: the master observes end-to-end latency there)
    enqueued_at: float = 0.0
    # device affinity: the pipeline instance this task was assigned to at
    # enqueue time and that instance's chip — recorded BEFORE loading so
    # the loader's device staging targets the chip that will actually
    # evaluate the task (a mismatch would silently copy cross-chip)
    instance: int = 0
    device: Optional[Any] = None
    # work-packet streaming (PerfParams.stream_work_packets): the task's
    # per-chunk plans, the loader->evaluator chunk queue, and the abort
    # handshake (evaluator failure must unblock a producing loader)
    chunk_plans: Optional[List[A.TaskPlan]] = None
    chunk_q: Optional["queue.Queue"] = None
    chunk_abort: Optional[threading.Event] = None
    # frame-cache page leases (engine/framecache.py): pages this task
    # gathers from stay pinned — ineligible for eviction — until
    # evaluation finishes (released by the executor; a finalizer on
    # this TaskItem is the abort backstop)
    cache_leases: Optional[List[Any]] = None
    # sharded gang members (engine/gang.py): per source node, the global
    # rows this member does NOT decode — its neighbors own them and the
    # post-load halo exchange delivers them over the mesh; halo_fill is
    # the hook that runs that exchange and splices the received rows
    # into the loaded elements before device prestaging
    halo_drop: Optional[Dict[int, Any]] = None
    halo_fill: Optional[Any] = None
    # rows the loader actually decoded/read for this task (set by
    # _load_task after any halo restriction) — the per-member decode
    # accounting the sharded-gang metrics report
    decode_rows: int = 0


class _StatefulChain:
    """Per-job planning chain for stateful task affinity
    (PerfParams.stateful_task_affinity; reference save_coordinator
    worker.cpp:373-415 packet pinning).

    Loaders plan a chained job's tasks in task order: `gate_plan` waits
    (briefly) until the preceding task was planned, then hands back the
    watermark map — the row each unbounded-state kernel's state will
    have advanced through — so analysis derives an incremental plan.
    The gate orders only the cheap PLAN step; decode still runs on all
    loader threads concurrently.  A timeout (a failed or reordered
    predecessor) degrades that one task to the self-contained plan;
    the chain then continues from its watermarks.  Correctness never
    depends on any of this: the evaluator re-verifies the premise
    against actual kernel state (StateCarryMiss -> self-contained
    re-run)."""

    GATE_TIMEOUT = 5.0

    def __init__(self):
        self.cond = threading.Condition()
        self.last_planned: Optional[int] = None
        # (unbounded node id, slice group) -> last row planned through
        self.water: Dict[Tuple[int, int], int] = {}

    def gate_plan(self, task_idx: int) -> Optional[Dict[Tuple[int, int],
                                                        int]]:
        """Block until `task_idx` is next in the chain (or timeout);
        returns the carry map, or None for a self-contained plan."""
        with self.cond:
            deadline = time.time() + self.GATE_TIMEOUT
            while self.last_planned is not None \
                    and task_idx > self.last_planned + 1:
                left = deadline - time.time()
                if left <= 0 or not self.cond.wait(timeout=left):
                    if deadline - time.time() <= 0:
                        break
            if self.last_planned is None \
                    or task_idx == self.last_planned + 1:
                return dict(self.water)
            return None

    def planned(self, task_idx: int,
                watermarks: Dict[Tuple[int, int], int]) -> None:
        with self.cond:
            if self.last_planned is None or task_idx > self.last_planned:
                self.last_planned = task_idx
            for k, m in watermarks.items():
                if m > self.water.get(k, -1):
                    self.water[k] = m
            self.cond.notify_all()


class LocalExecutor:
    def __init__(self, db: Database, profiler: Optional[Profiler] = None,
                 num_load_workers: int = 2, num_save_workers: int = 2,
                 pipeline_instances: int = 1, node_id: int = 0,
                 decoder_threads: int = 1):
        self.db = db
        self.profiler = profiler or Profiler()
        self.num_load_workers = num_load_workers
        self.num_save_workers = num_save_workers
        self.pipeline_instances = pipeline_instances
        self.node_id = node_id
        # libav threads per decoder handle (frame threading); total decode
        # parallelism = num_load_workers x decoder_threads
        self.decoder_threads = decoder_threads
        # per-graph memo for _column_device_bound (keyed by GraphInfo
        # identity; cleared when a different graph runs).  Locked: loader
        # threads share it and a concurrent clear() mid-read would KeyError
        self._device_bound_cache: Dict[Any, Any] = {}
        self._device_bound_lock = threading.Lock()
        # job idx -> _StatefulChain when stateful task affinity is active
        self._chains: Dict[int, _StatefulChain] = {}
        # PerfParams.stream_work_packets, latched per run/bulk
        self._stream_opt = True
        # span sink for this executor's task/stage/op spans; a cluster
        # Worker swaps in its own export-enabled tracer so spans ship to
        # the master (ShipSpans)
        self.tracer = _tr.default_tracer()
        # trace_id of the last local run (Client.trace reads it)
        self.last_trace_id: Optional[str] = None
        # frame-cache source identity: table ids are per-database and
        # restart at 0 (and a database re-created at the same root
        # would restart them too), so pages are keyed under a
        # per-backend-object (root, seq) identity — no two Database
        # objects in one process can ever alias each other's pages
        # (engine/framecache.py db_cache_key)
        self._cache_db_key = _fc.db_cache_key(db.backend)

    # ------------------------------------------------------------------
    # Job-set preparation (reference master.cpp:1367 process_job admission)
    # ------------------------------------------------------------------

    def prepare(self, outputs: Sequence[O.OpNode], perf: PerfParams,
                cache_mode: CacheMode = CacheMode.Error
                ) -> Tuple[A.GraphInfo, List[JobContext]]:
        info = A.analyze(outputs)
        perf = self._estimate_perf(info, perf)
        jobs: List[JobContext] = []
        for j in range(info.num_jobs):
            jobs.append(self._prepare_job(info, j, perf, cache_mode))
        return info, jobs

    def prepare_readonly(self, outputs: Sequence[O.OpNode], perf: PerfParams
                         ) -> Tuple[A.GraphInfo, List[JobContext]]:
        """Worker-side preparation: identical analysis but output tables
        were already created by the master — look them up instead of
        creating (reference workers re-run DAG analysis, worker.cpp:1013)."""
        info = A.analyze(outputs)
        perf = self._estimate_perf(info, perf)
        jobs: List[JobContext] = []
        for j in range(info.num_jobs):
            jobs.append(self._prepare_job(info, j, perf,
                                          CacheMode.Overwrite,
                                          create_tables=False))
        return info, jobs

    def _bind_if_unbound(self, stream) -> None:
        """Re-bind a stream that traveled over RPC: __getstate__ nulls its
        client (streams.py), so `_sc is None` — distinct from a missing
        attribute (non-stream objects) — means 'needs this executor's
        db'."""
        if getattr(stream, "_sc", False) is None:
            stream.bind(self.db)

    def _estimate_perf(self, info: A.GraphInfo, perf: PerfParams
                       ) -> PerfParams:
        if not getattr(perf, "_estimate", False):
            if perf.io_packet_size % perf.work_packet_size != 0:
                raise ScannerException(
                    "io_packet_size must be a multiple of work_packet_size")
            return perf
        # geometry-aware sizing (the reference's PerfParams.estimate
        # analog, common.py:78-160): target ~64 MB of decoded frames per
        # io packet so tasks neither thrash tiny items nor blow host RAM
        frame_bytes = 0
        keyint = 0
        for n in info.sources:
            for s in n.extra["streams"]:
                self._bind_if_unbound(s)
                if getattr(s, "is_video", False) \
                        and hasattr(s, "estimate_geometry"):
                    # real errors (bad path, storage failure) propagate:
                    # silently mis-sizing a 4K stream as VGA would blow
                    # host RAM far from the actual cause
                    fb, ki = s.estimate_geometry()
                    frame_bytes = max(frame_bytes, fb)
                    keyint = max(keyint, ki)
                elif getattr(s, "is_video", False) \
                        and hasattr(s, "estimate_size"):
                    frame_bytes = max(frame_bytes, s.estimate_size())
        if frame_bytes > 0:
            target = 64 << 20
            io = max(16, min(512, target // frame_bytes))

            def best_work(n: int):
                """Best divisor of n in [4, 16] (compute batch floor:
                1-row work packets drown in scheduling overhead).
                Powers of two are preferred so steady-state work packets
                land exactly on a bucket of the shape-stable kernel
                dispatch (engine/evaluate.py bucket_ladder) — a full
                chunk then never pads."""
                for w in (16, 8, 4):
                    if n % w == 0:
                        return w
                for w in range(min(16, n), 3, -1):
                    if n % w == 0:
                        return w
                return None

            # snap io packets to a multiple of the keyframe interval so
            # task boundaries land on keyframes: a mid-GOP task start
            # re-decodes the GOP prefix (up to keyint-1 frames) for
            # nothing.  The snap is dropped rather than accepted when it
            # would cross the 16-frame floor (round up instead) or leave
            # no workable packet divisor.
            work = None
            if keyint > 1 and keyint <= 2 * io:
                snapped = (io // keyint) * keyint
                if snapped < 16:
                    snapped += keyint
                w = best_work(snapped)
                if w is not None:
                    io, work = snapped, w
            if work is None:
                # round down to a power of two: the work packet is the
                # kernel call shape, and a pow2 packet is its own bucket
                work = max(4, min(16, io // 4))
                work = 1 << (int(work).bit_length() - 1)
                io = (io // work) * work
            perf.io_packet_size = int(io)
            perf.work_packet_size = int(work)
        else:
            perf.io_packet_size = 512
            perf.work_packet_size = 128
        # resolution happens exactly once: cluster workers receive the
        # concrete sizes and must not re-estimate (estimate_size does I/O
        # and could diverge from the master's task partitioning)
        perf._estimate = False  # type: ignore[attr-defined]
        return perf

    def _prepare_job(self, info: A.GraphInfo, j: int, perf: PerfParams,
                     cache_mode: CacheMode,
                     create_tables: bool = True) -> JobContext:
        # resolve sources
        source_info: Dict[int, Dict[str, Any]] = {}
        source_rows: Dict[int, int] = {}
        fps = 30.0
        for n in info.sources:
            stream: StoredStream = n.extra["streams"][j]
            self._bind_if_unbound(stream)
            if getattr(stream, "is_custom", False):
                # pluggable source (reference Source::read extension point)
                source_info[n.id] = {"custom": stream, "is_video": False}
                source_rows[n.id] = stream.len()
                continue
            if isinstance(stream, NamedVideoStream):
                stream.ensure_ingested()
            if not stream.committed():
                raise JobException(
                    f"input stream {stream.name} does not exist or is "
                    f"not committed")
            desc = self.db.table_descriptor(stream.name)
            col = stream.column if stream.column in desc.column_names() \
                else next(c for c in desc.column_names() if c != "index")
            is_video = desc.column_type(col) == md.ColumnType.VIDEO
            vinfo = None
            if is_video:
                from ..video import load_video_meta
                vinfo = load_video_meta(self.db, stream.name, col)
                if vinfo.fps:
                    fps = vinfo.fps
            codec = next((c.codec for c in desc.columns if c.name == col),
                         "raw")
            source_info[n.id] = {
                "table": desc, "column": col, "is_video": is_video,
                "video_meta": vinfo, "codec": codec,
            }
            source_rows[n.id] = desc.num_rows

        jr = A.job_rows(info, j, source_rows)
        jr.work_packet_size = int(perf.work_packet_size)
        tasks = A.generate_tasks(jr, perf.io_packet_size)

        # output tables (pre-created uncommitted, reference
        # master.cpp:1619-1663).  CacheMode.Ignore skips the job only when
        # EVERY sink output already exists committed (job-level resume,
        # reference client.py:1389-1430)
        custom_sinks: Dict[int, Any] = {}
        sink_names = []
        table_sinks = []
        for sink in info.sinks:
            out_stream = sink.extra["streams"][j]
            self._bind_if_unbound(out_stream)
            if getattr(out_stream, "is_custom", False):
                # CacheMode applies to custom sinks too: stale rows from a
                # previous (longer) run must not survive an Overwrite
                if create_tables and out_stream.storage.exists(out_stream):
                    if cache_mode == CacheMode.Error:
                        raise JobException(
                            f"custom output {out_stream.name} already "
                            f"exists (pass cache_mode=CacheMode.Overwrite)")
                    if cache_mode == CacheMode.Overwrite:
                        out_stream.storage.delete_stream(out_stream)
                custom_sinks[sink.id] = out_stream
                continue
            table_sinks.append(sink)
            sink_names.append(out_stream.name if hasattr(out_stream, "name")
                              else str(out_stream))
        if not create_tables:
            sink_tables = {}
            for sink, name in zip(table_sinks, sink_names):
                if not self.db.has_table(name):
                    continue  # job skipped by the master
                src_col = sink.input_columns()[0]
                codec = self._codec_for(src_col)
                desc = self.db.table_descriptor(name)
                enc = dict(sink.extra.get("encode_options") or {})
                sink_tables[sink.id] = (desc, desc.columns[0].name, codec,
                                        enc)
            return JobContext(job_idx=j, jr=jr, tasks=tasks,
                          sparsity_threshold=int(perf.load_sparsity_threshold),
                              source_info=source_info,
                              sink_tables=sink_tables, fps=fps,
                              custom_sinks=custom_sinks,
                              skipped=not sink_tables and not custom_sinks)
        if table_sinks and not custom_sinks \
                and cache_mode == CacheMode.Ignore and all(
                self.db.table_is_committed(nm) for nm in sink_names):
            return JobContext(job_idx=j, jr=jr, tasks=tasks,
                          sparsity_threshold=int(perf.load_sparsity_threshold),
                              source_info=source_info, sink_tables={},
                              fps=fps, skipped=True)
        sink_tables: Dict[int, Tuple] = {}
        for sink, name in zip(table_sinks, sink_names):
            src_col = sink.input_columns()[0]
            codec = self._codec_for(src_col)
            if self.db.has_table(name):
                if self.db.table_is_committed(name) \
                        and cache_mode == CacheMode.Error:
                    raise JobException(
                        f"output stream {name} already exists "
                        f"(pass cache_mode=CacheMode.Overwrite or Ignore)")
                self.db.delete_table(name)
            is_frame = codec == "frame"
            col = md.ColumnDescriptor(
                "frame" if is_frame else "output",
                md.ColumnType.VIDEO if is_frame else md.ColumnType.BYTES,
                codec="video" if is_frame else codec)
            desc = self.db.create_table(
                name, [col], end_rows=[e for _, e in tasks], job_id=-1)
            enc = dict(sink.extra.get("encode_options") or {})
            sink_tables[sink.id] = (desc, col.name, codec, enc)
        ctx = JobContext(job_idx=j, jr=jr, tasks=tasks,
                          sparsity_threshold=int(perf.load_sparsity_threshold),
                         source_info=source_info, sink_tables=sink_tables,
                         fps=fps, custom_sinks=custom_sinks,
                         skipped=not sink_tables and not custom_sinks)
        return ctx

    @staticmethod
    def _codec_for(col: O.OpColumn) -> str:
        node = col.op
        if node.is_builtin:
            return "frame" if col.is_frame else "pickle"
        idx = [c for c, _ in node.spec.output_columns].index(col.column)
        return node.spec.output_codecs[idx]

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------

    def setup_chains(self, info: A.GraphInfo, jobs: List[JobContext],
                     perf: PerfParams) -> None:
        """Arm stateful task affinity (one planning chain per multi-task
        job) when the graph has unbounded-state ops and the caller opted
        in.  NOTE: the whole run then executes with ONE loader and ONE
        pipeline instance on this node (kernel state lives in a single
        instance's kernels, and reordering would carry-miss) — an
        explicit trade the opt-in knob documents; cross-job parallelism
        in a cluster comes from per-job worker stickiness."""
        self._chains = {}
        if not getattr(perf, "stateful_task_affinity", False):
            return
        unbounded = [n.name for n in info.ops
                     if n.spec is not None
                     and getattr(n.spec, "unbounded_state", False)]
        if not unbounded:
            return
        for job in jobs:
            if not job.skipped and len(job.tasks) > 1:
                self._chains[job.job_idx] = _StatefulChain()
        if self._chains:
            _log.info(
                "stateful task affinity armed for %d job(s) (ops: %s): "
                "incremental plans, single evaluation instance",
                len(self._chains), ", ".join(sorted(set(unbounded))))

    # -- tracing glue (util/tracing.py) --------------------------------

    def _task_trace_begin(self, w: TaskItem) -> None:
        """Open the task's span (idempotent): child of its trace context
        — the job root span locally, the master's assign span in
        cluster mode.  No context = no span (tracing off or untraced
        caller); every stage then runs trace-free at one None check."""
        if w.trace_span is None and w.trace_ctx is not None:
            w.trace_span = _tr.open_span(
                self.tracer, "task", parent=w.trace_ctx,
                job=w.job.job_idx, task=w.task_idx, attempt=w.attempt)

    def _task_scope(self, w: TaskItem):
        """Resume the task span on the calling stage thread, so the
        stage/op profiler spans inside nest under it."""
        return _tr.use_span(self.tracer, w.trace_span)

    def _task_trace_end(self, w: TaskItem,
                        status: Optional[str] = None) -> None:
        span, w.trace_span = w.trace_span, None
        _tr.close_span(self.tracer, span, status=status)
        if w.enqueued_at:
            # enqueue -> sink-committed (or terminal failure; errors are
            # latency too in a serving SLO)
            _M_TASK_LATENCY.observe(time.time() - w.enqueued_at)

    def run(self, outputs: Sequence[O.OpNode], perf: PerfParams,
            cache_mode: CacheMode = CacheMode.Error,
            show_progress: bool = False) -> List[JobContext]:
        info, jobs = self.prepare(outputs, perf, cache_mode)
        self.setup_chains(info, jobs, perf)
        self._stream_opt = bool(getattr(perf, "stream_work_packets", True))
        self.profiler.level = int(getattr(perf, "profiler_level", 1))
        work = [TaskItem(job, t, rng)
                for job in jobs if not job.skipped
                for t, rng in enumerate(job.tasks)]
        _log.info("job set prepared: %d jobs (%d skipped), %d tasks",
                  len(jobs), sum(1 for j in jobs if j.skipped), len(work))
        # the job's root trace span: every task span of this run chains
        # up to it under one trace_id (Client.trace assembles the tree)
        root = _tr.open_span(self.tracer, "job",
                             tasks=len(work), jobs=len(jobs))
        self.last_trace_id = root.trace_id if root is not None else None
        now = time.time()
        for w in work:
            if root is not None:
                w.trace_ctx = root.context()
            w.enqueued_at = now
        try:
            if work:
                # level >= 2: capture the XLA device timeline around the
                # job (SURVEY §5; merged into Profile.write_trace output)
                from ..util.jaxprof import device_trace
                with device_trace(self.profiler):
                    self._run_pipeline(
                        info, work, show_progress,
                        queue_size=int(perf.queue_size_per_pipeline),
                        precompile=self.precompile_hint(jobs))
        finally:
            _tr.close_span(self.tracer, root)
        for job in jobs:
            if job.skipped:
                continue
            for desc, _c, _k, _e in job.sink_tables.values():
                self.db.commit_table(desc.id)
            for stream in job.custom_sinks.values():
                # durability barrier (reference Sink::finished)
                stream.storage.finished(stream, job.jr.output_rows)
        self.db.write_megafile()
        return jobs

    @staticmethod
    def precompile_hint(jobs: List[JobContext]
                        ) -> Optional[Tuple[int, int, int]]:
        """(frame_h, frame_w, work_packet_size) for the evaluator's
        bucket-ladder warm-up (evaluate.py precompile), from the first
        non-skipped job with a video source — the geometry the device
        kernels will actually see.  None = nothing to warm."""
        for job in jobs:
            if getattr(job, "skipped", False):
                continue
            for si in job.source_info.values():
                vm = si.get("video_meta")
                if vm is not None and vm.height and vm.width:
                    wp = int(getattr(job.jr, "work_packet_size", 0) or 0)
                    return (int(vm.height), int(vm.width), wp)
        return None

    def _run_pipeline(self, info: A.GraphInfo, work: List[TaskItem],
                      show_progress: bool,
                      queue_size: Optional[int] = None,
                      precompile: Optional[Tuple[int, int, int]] = None
                      ) -> None:
        pending = list(work)
        src_lock = threading.Lock()

        def source():
            with src_lock:
                return pending.pop(0) if pending else None

        done = self.run_pipeline(info, source, show_progress=show_progress,
                                 total=len(work), queue_size=queue_size,
                                 precompile=precompile)
        if done != len(work):
            raise JobException(
                f"pipeline finished {done}/{len(work)} tasks")

    def run_pipeline(self, info: A.GraphInfo, source,
                     on_start=None, on_done=None, on_eval_done=None,
                     on_task_error=None,
                     evaluator_factory=None, close_evaluators: bool = True,
                     queue_size: Optional[int] = None,
                     show_progress: bool = False, total: int = 0,
                     precompile: Optional[Tuple[int, int, int]] = None
                     ) -> int:
        """Multi-stage streaming pipeline (reference worker.cpp:1467-1724
        load/evaluate/save stage drivers): N loaders pull TaskItems from
        `source` and decode, P evaluator instances execute, S savers
        persist.  Shared by the local executor (source = task list) and the
        cluster worker (source = master NextWork pull), so a cluster worker
        keeps every stage of the node busy instead of running one task at a
        time.

        source() -> TaskItem | "wait" (retry shortly) | None (exhausted);
        called concurrently from loader threads.
        on_start(w) -> bool | None: evaluation-begin hook (cluster:
        StartedWork RPC); returning False drops the task without
        evaluating (revoked attempt).  on_eval_done(w): evaluation-complete
        hook, fired when the task hands off to the save stage (cluster:
        EvalDone RPC so save-parked tasks stop counting against the
        NextWork window).  on_done(w): save-complete hook (cluster:
        FinishedWork RPC).
        on_task_error(w, exc) -> bool: True = task failure is reported and
        the pipeline continues (cluster); False/None = abort (local).
        evaluator_factory(idx, skip_fetch) -> TaskEvaluator: override to
        reuse evaluators across pipeline entries (cluster worker).
        Returns the number of tasks fully saved.

        SCANNER_TPU_NO_PIPELINING=1 (reference worker.cpp:140 NO_PIPELINING)
        degrades to a single-threaded sequential loop — same semantics,
        clean stack traces for debugging."""
        import os
        # the health/SLO engine watches this pipeline's queue-depth and
        # stage-rate series: make sure it samples while stages run,
        # even when no Client/Worker constructor started it (direct
        # LocalExecutor embedding, spawned test workers)
        from ..util import health as _health
        _health.ensure_started()
        # and the remediation controller rides the same alerts: local
        # runs get the worker-local playbooks (frame-cache shrink,
        # ladder re-warm) with no cluster in sight
        from . import controller as _controller
        _controller.ensure_started()
        if os.environ.get("SCANNER_TPU_NO_PIPELINING", "0") not in \
                ("0", "", "false"):
            return self._run_serial(info, source, on_start, on_done,
                                    on_eval_done, on_task_error,
                                    evaluator_factory, close_evaluators,
                                    show_progress, total, precompile)
        qsize = queue_size or 4
        # stateful affinity: kernel state lives in ONE instance's kernels,
        # so a chained run serializes evaluation (the reference pins a
        # job's packets to one worker for the same reason).  One loader
        # too: with N loaders, a decode-time inversion hands the
        # evaluator task t+1 before t and every inversion costs a
        # StateCarryMiss reload+recompute — per-task decode parallelism
        # stays available via decoder_threads.
        # ANY stateful op serializes the same way, chained or not: a
        # bounded-state kernel's maybe_reset only fires on row
        # DISCONTINUITY, so an inverted first task (fresh instance,
        # _last_row still None) would run on virgin state with no reset
        # and no carry-miss to catch it — order is correctness here,
        # not a perf knob.
        stateful = any(n.spec is not None and n.spec.is_stateful
                       for n in info.ops)
        serialize = bool(self._chains) or stateful
        n_evals = 1 if serialize else self.pipeline_instances
        n_loaders = 1 if serialize else self.num_load_workers
        # Device-affine routing: when instances own distinct chips, each
        # gets its OWN queue and the loader assigns each task to the
        # least-loaded instance (round-robin tie-break) at enqueue time
        # — the assignment is recorded on the TaskItem before loading so
        # device staging targets the chip that will evaluate the task.
        # A chained run (n_evals=1) or a single-chip host keeps today's
        # shared queue (any instance takes any task).
        from .evaluate import assigned_device, device_label
        inst_devices = [assigned_device(i) for i in range(n_evals)]
        if n_evals > 1 and any(d is not None for d in inst_devices):
            eval_qs: List["queue.Queue"] = [queue.Queue(maxsize=qsize)
                                            for _ in range(n_evals)]
        else:
            shared_q: "queue.Queue" = queue.Queue(maxsize=qsize)
            eval_qs = [shared_q] * n_evals
        uniq_qs = list({id(q): q for q in eval_qs}.values())
        save_q: "queue.Queue" = queue.Queue(maxsize=qsize)
        # live depth gauges sample the queues at scrape time; the last
        # pipeline to start owns the gauge (concurrent pipelines in one
        # process share the process registry)
        depth_fns = {
            "evaluate": lambda: sum(q.qsize() for q in uniq_qs),
            "save": save_q.qsize,
        }
        for stage, fn in depth_fns.items():
            _M_QDEPTH.labels(stage=stage).set_function(fn)
        errors: List[BaseException] = []
        err_lock = threading.Lock()
        stop = threading.Event()

        def record_err(e: BaseException):
            with err_lock:
                errors.append(e)
            stop.set()

        def task_failed(w: TaskItem, e: BaseException) -> None:
            """Route one task's failure; abort unless the error handler
            accepts it (cluster mode reports FailedWork and moves on)."""
            if w.trace_span is not None:
                w.trace_span.add_event("error", type=type(e).__name__,
                                       message=str(e)[:200])
            self._task_trace_end(w, status="error")
            # drop the failed attempt's staged columns/results NOW: a
            # task requeued after memory pressure must not keep holding
            # the very device buffers that caused it (the ledger
            # releases as the arrays are collected; cache pins likewise
            # must not outlive the attempt)
            w.elements = None
            w.results = None
            self._release_cache(w)
            if on_task_error is not None and on_task_error(w, e):
                return
            _log.exception("task (%d,%d) failed; aborting pipeline",
                           w.job.job_idx, w.task_idx, exc_info=e)
            record_err(e)

        # loader cache: (thread, job, node) -> DecoderAutomata
        tls = threading.local()

        # Enqueue-time instance assignment: fixes which evaluator — and
        # therefore which chip — a task runs on, BEFORE the loader
        # stages its columns.  Least-loaded queue wins so one slow task
        # can't head-of-line-block the whole pipeline (strict
        # round-robin would keep feeding the slow instance until its
        # queue filled and the loader stalled while other chips
        # drained); a rotating start index breaks qsize ties fairly, so
        # an idle pipeline still spreads tasks across every chip.
        assign_lock = threading.Lock()
        assign_counter = [0]

        def assign_instance(w: TaskItem) -> None:
            with assign_lock:
                start = assign_counter[0] % n_evals
                assign_counter[0] += 1
            best = min(
                range(n_evals),
                key=lambda k: (eval_qs[(start + k) % n_evals].qsize(), k))
            idx = (start + best) % n_evals
            w.instance = idx
            w.device = inst_devices[idx]

        def loader():
            try:
                try:
                    while not stop.is_set():
                        w = source()
                        if w is None:
                            break
                        if w == "wait":
                            time.sleep(0.2)
                            continue
                        assign_instance(w)
                        self._task_trace_begin(w)
                        try:
                            with self._task_scope(w):
                                self.load_task(info, w, tls)
                        except Exception as e:  # noqa: BLE001
                            task_failed(w, e)
                            continue
                        placed = False
                        while not stop.is_set():
                            try:
                                eval_qs[w.instance].put(w, timeout=0.25)
                                placed = True
                                break
                            except queue.Full:
                                pass
                        if placed and w.chunk_plans is not None:
                            # streaming task: decode chunks into its
                            # bounded queue while the evaluator consumes
                            with self._task_scope(w):
                                self._produce_chunks(info, w, tls,
                                                     stop=stop)
                finally:
                    # release decoder handles held by this loader thread
                    for auto in getattr(tls, "automata", {}).values():
                        auto.close()
                    if hasattr(tls, "automata"):
                        tls.automata = {}
            except BaseException as e:  # noqa: BLE001
                record_err(e)

        def make_evaluator(idx: int, skip_fetch: bool) -> TaskEvaluator:
            if evaluator_factory is not None:
                return evaluator_factory(idx, skip_fetch)
            return TaskEvaluator(info, self.profiler,
                                 skip_fetch_resources=skip_fetch,
                                 precompile=precompile,
                                 instance=idx, instances=n_evals)

        def evaluator(evaluator_idx: int):
            te = None
            my_q = eval_qs[evaluator_idx]
            import types
            fb_tls = types.SimpleNamespace()  # fallback reload decoders
            try:
                # fetch_resources runs once per node: instance 0 fetches,
                # the rest only setup (reference evaluate_worker.cpp:488-534)
                if evaluator_idx > 0:
                    fetch_done.wait()
                te = make_evaluator(evaluator_idx, evaluator_idx > 0)
                if evaluator_idx == 0:
                    fetch_done.set()
                while not stop.is_set():
                    try:
                        w: TaskItem = my_q.get(timeout=0.25)
                    except queue.Empty:
                        if loaders_done.is_set() and my_q.empty():
                            break
                        continue
                    if w is _SENTINEL:
                        break
                    try:
                        if on_start is not None and on_start(w) is False:
                            if w.chunk_abort is not None:
                                w.chunk_abort.set()  # unblock the loader
                            # leases the producing loader adds after
                            # this are released by its abort path
                            self._release_cache(w)
                            self._task_trace_end(w, status="revoked")
                            continue  # revoked attempt: drop silently
                        t0 = time.time()
                        with self._task_scope(w), \
                                self.profiler.span("evaluate", level=0,
                                                   task=w.task_idx,
                                                   job=w.job.job_idx):
                            if w.chunk_q is not None:
                                w.results = self._consume_chunks(
                                    info, te, w, fb_tls, stop=stop)
                            else:
                                w.results = self._evaluate_with_fallback(
                                    info, te, w, fb_tls)
                        # start the sink d2h now: the copy rides under
                        # the NEXT task's evaluation instead of blocking
                        # the saver (~180 ms per fetch over the tunnel)
                        self._prefetch_results(w)
                        dt = time.time() - t0
                        _M_STAGE_SECONDS.labels(stage="evaluate").inc(dt)
                        _M_STAGE_TASKS.labels(stage="evaluate").inc()
                        lbl = device_label(w.device)
                        _M_DEV_TASKS.labels(device=lbl).inc()
                        _M_DEV_BUSY.labels(device=lbl).inc(dt)
                        w.elements = None
                        # evaluation is done with the cached pages:
                        # unpin them (the sink batches are the task's
                        # own arrays, never cache pages)
                        self._release_cache(w)
                    except Exception as e:  # noqa: BLE001
                        task_failed(w, e)
                        continue
                    if on_eval_done is not None:
                        on_eval_done(w)
                    while not stop.is_set():
                        try:
                            save_q.put(w, timeout=0.25)
                            break
                        except queue.Full:
                            pass
            except BaseException as e:  # noqa: BLE001
                record_err(e)
            finally:
                fetch_done.set()  # never leave siblings waiting
                for auto in getattr(fb_tls, "automata", {}).values():
                    auto.close()
                if te is not None and close_evaluators:
                    te.close()

        done_count = [0]
        done_lock = threading.Lock()

        def saver():
            try:
                while not stop.is_set():
                    try:
                        w: TaskItem = save_q.get(timeout=0.25)
                    except queue.Empty:
                        if evals_done.is_set() and save_q.empty():
                            break
                        continue
                    try:
                        t0 = time.time()
                        with self._task_scope(w):
                            with self.profiler.span("save", level=0,
                                                    task=w.task_idx,
                                                    job=w.job.job_idx):
                                self._save_task(info, w)
                        _M_STAGE_SECONDS.labels(stage="save").inc(
                            time.time() - t0)
                        _M_STAGE_TASKS.labels(stage="save").inc()
                        # close the span BEFORE on_done: the cluster
                        # worker's completion hook ships spans then sends
                        # FinishedWork, so the master holds this task's
                        # full chain before the bulk can finish
                        self._task_trace_end(w)
                        if on_done is not None:
                            on_done(w)
                    except Exception as e:  # noqa: BLE001
                        task_failed(w, e)
                        continue
                    with done_lock:
                        done_count[0] += 1
                        if show_progress:
                            print(f"\rtasks {done_count[0]}/{total}",
                                  end="", flush=True)
            except BaseException as e:  # noqa: BLE001
                record_err(e)

        fetch_done = threading.Event()
        loaders_done = threading.Event()
        evals_done = threading.Event()

        loaders = [threading.Thread(target=loader, name=f"load-{i}")
                   for i in range(n_loaders)]
        evals = [threading.Thread(target=evaluator, args=(i,),
                                  name=f"eval-{i}")
                 for i in range(n_evals)]
        savers = [threading.Thread(target=saver, name=f"save-{i}")
                  for i in range(self.num_save_workers)]
        try:
            for t in loaders + evals + savers:
                t.start()
            for t in loaders:
                t.join()
            loaders_done.set()
            for t in evals:
                t.join()
            evals_done.set()
            for t in savers:
                t.join()
        finally:
            # detach the depth gauges from this run's (now dead) queues —
            # but only if this run still owns them: a concurrent pipeline
            # that re-bound the gauge keeps its live sampler
            for stage, fn in depth_fns.items():
                g = _M_QDEPTH.labels(stage=stage)
                if g.clear_function(fn):
                    g.set(0)
        if show_progress:
            print()
        if errors:
            raise errors[0]
        return done_count[0]

    def _run_serial(self, info: A.GraphInfo, source, on_start, on_done,
                    on_eval_done, on_task_error, evaluator_factory,
                    close_evaluators: bool, show_progress: bool,
                    total: int,
                    precompile: Optional[Tuple[int, int, int]] = None
                    ) -> int:
        """The NO_PIPELINING path: every stage inline on this thread."""
        import types
        from .evaluate import device_label
        tls = types.SimpleNamespace()
        fb_tls = types.SimpleNamespace()  # carry-miss fallback decoders
        if evaluator_factory is not None:
            te = evaluator_factory(0, False)
        else:
            te = TaskEvaluator(info, self.profiler, precompile=precompile)
        done = 0
        try:
            while True:
                w = source()
                if w is None:
                    break
                if w == "wait":
                    time.sleep(0.2)
                    continue
                # single inline instance: staging still targets its
                # assigned chip so serial runs match the threaded path
                w.device = te.device
                # Error routing mirrors the threaded path stage by stage:
                # load / evaluate(+on_start) / save(+on_done) failures are
                # task failures (on_task_error may absorb them), while an
                # on_eval_done failure — cluster bookkeeping RPC, not task
                # work — is a pipeline error and propagates (the threaded
                # evaluator calls it outside its per-task try).
                self._task_trace_begin(w)
                try:
                    with self._task_scope(w):
                        self.load_task(info, w, tls)
                    if on_start is not None and on_start(w) is False:
                        self._release_cache(w)
                        self._task_trace_end(w, status="revoked")
                        continue  # revoked attempt
                    t0 = time.time()
                    with self._task_scope(w), \
                            self.profiler.span("evaluate", level=0,
                                               task=w.task_idx,
                                               job=w.job.job_idx):
                        if w.chunk_plans is not None:
                            # inline streaming on this one thread; the
                            # carry-miss fallback loads through fb_tls —
                            # NOT tls, whose decoder sessions are
                            # suspended mid-run and must not be reset
                            w.results = self._consume_iter(
                                info, te, w,
                                self._iter_chunk_items(info, w, tls),
                                fb_tls)
                        else:
                            w.results = self._evaluate_with_fallback(
                                info, te, w, fb_tls)
                    self._prefetch_results(w)
                    dt = time.time() - t0
                    _M_STAGE_SECONDS.labels(stage="evaluate").inc(dt)
                    _M_STAGE_TASKS.labels(stage="evaluate").inc()
                    lbl = device_label(w.device)
                    _M_DEV_TASKS.labels(device=lbl).inc()
                    _M_DEV_BUSY.labels(device=lbl).inc(dt)
                    w.elements = None
                    self._release_cache(w)
                except Exception as e:  # noqa: BLE001
                    if w.trace_span is not None:
                        w.trace_span.add_event(
                            "error", type=type(e).__name__,
                            message=str(e)[:200])
                    self._task_trace_end(w, status="error")
                    w.elements = None
                    w.results = None
                    self._release_cache(w)
                    if on_task_error is not None and on_task_error(w, e):
                        continue
                    raise
                if on_eval_done is not None:
                    on_eval_done(w)
                try:
                    t0 = time.time()
                    with self._task_scope(w):
                        with self.profiler.span("save", level=0,
                                                task=w.task_idx,
                                                job=w.job.job_idx):
                            self._save_task(info, w)
                    _M_STAGE_SECONDS.labels(stage="save").inc(
                        time.time() - t0)
                    _M_STAGE_TASKS.labels(stage="save").inc()
                    self._task_trace_end(w)
                    if on_done is not None:
                        on_done(w)
                except Exception as e:  # noqa: BLE001
                    if w.trace_span is not None:
                        w.trace_span.add_event(
                            "error", type=type(e).__name__,
                            message=str(e)[:200])
                    self._task_trace_end(w, status="error")
                    w.elements = None
                    w.results = None
                    if on_task_error is not None and on_task_error(w, e):
                        continue
                    raise
                done += 1
                if show_progress:
                    print(f"\rtasks {done}/{total}", end="", flush=True)
        finally:
            for ns in (tls, fb_tls):
                for auto in getattr(ns, "automata", {}).values():
                    auto.close()
            if close_evaluators:
                te.close()
        if show_progress:
            print()
        return done

    # ------------------------------------------------------------------

    def run_single_task(self, info: A.GraphInfo, w: TaskItem,
                        save: bool = True,
                        span_attrs: Optional[Dict[str, Any]] = None
                        ) -> TaskItem:
        """Run ONE task stage-inline on this thread: load → evaluate
        (→ save).  The gang-member path (engine/gang.py): a gang task
        executes inside a dedicated member process, synchronized with
        its peers by collectives rather than by the streaming pipeline,
        and only member 0 saves — so the member defers `save` until the
        cross-host agreement check passes (`save_results` finishes the
        job).  `span_attrs` land on the task span (gang id / epoch /
        member rank, so per-host stragglers stay attributable under the
        gang root span).  Returns `w` with `.results` populated."""
        import types
        tls = types.SimpleNamespace()
        fb_tls = types.SimpleNamespace()
        if w.trace_span is None and w.trace_ctx is not None:
            w.trace_span = _tr.open_span(
                self.tracer, "task", parent=w.trace_ctx,
                job=w.job.job_idx, task=w.task_idx, attempt=w.attempt,
                **(span_attrs or {}))
        te = None
        try:
            with self._task_scope(w):
                self.load_task(info, w, tls)
            te = TaskEvaluator(info, self.profiler)
            w.device = te.device
            with self._task_scope(w), \
                    self.profiler.span("evaluate", level=0,
                                       task=w.task_idx,
                                       job=w.job.job_idx):
                w.results = self._evaluate_with_fallback(
                    info, te, w, fb_tls)
            w.elements = None
            self._release_cache(w)
            if save:
                self.save_results(info, w)
            return w
        except Exception as e:  # noqa: BLE001
            if w.trace_span is not None:
                w.trace_span.add_event("error", type=type(e).__name__,
                                       message=str(e)[:200])
            self._task_trace_end(w, status="error")
            w.elements = None
            w.results = None
            self._release_cache(w)
            raise
        finally:
            for auto in getattr(tls, "automata", {}).values():
                auto.close()
            if te is not None:
                te.close()

    def save_results(self, info: A.GraphInfo, w: TaskItem) -> None:
        """Persist a task's evaluated results and close its span — the
        deferred half of `run_single_task(save=False)`, run by a gang's
        single writer (member 0) only after the collective agreement
        check passed."""
        with self._task_scope(w):
            with self.profiler.span("save", level=0, task=w.task_idx,
                                    job=w.job.job_idx):
                self._save_task(info, w)
        self._task_trace_end(w)

    # ------------------------------------------------------------------
    # Work-packet streaming (PerfParams.stream_work_packets)
    # ------------------------------------------------------------------

    class _VideoFeed:
        """Incremental frame supply for one video source node of one
        streaming task: per-item decoder sessions
        (DecoderAutomata.stream_frames) chained in row order, a small
        row->frame buffer, and retention driven by the later chunks'
        minimum row so stencil back-reach is served from memory instead
        of a per-chunk keyframe re-decode (the reference's element
        cache, evaluate_worker.h:207-218)."""

        def __init__(self, ex: "LocalExecutor", w: TaskItem, tls,
                     node_id: int, si, plans: List[A.TaskPlan],
                     output_format: str, use_cache: bool = False):
            desc = si["table"]
            all_rows = np.unique(np.concatenate([
                np.asarray(p.source_rows[node_id], np.int64)
                for p in plans]))
            # suffix minima: after serving chunk i, rows below the
            # smallest row any LATER chunk requests can be dropped
            mins = [int(np.asarray(p.source_rows[node_id]).min())
                    if len(p.source_rows[node_id]) else np.iinfo(np.int64).max
                    for p in plans]
            suffix = []
            cur = np.iinfo(np.int64).max
            for m in reversed(mins):
                suffix.append(cur)
                cur = min(cur, m)
            self._keep_from = list(reversed(suffix))  # per chunk index
            self._chunk_i = 0
            self._buf: Dict[int, Any] = {}

            # decode in slices matched to the chunk row count so peak
            # scratch/buffer is ~one work packet, not a fixed constant
            wp_est = max(4, max(len(p.source_rows[node_id])
                                for p in plans))

            # the streamable guard (load_task) pins the task to ONE
            # item; its own descriptor drives the convert-mark geometry
            # (items of one table may differ — same rule as the
            # whole-task loader's per-item marks)
            item = desc.item_of_row(int(all_rows[0]))
            item_start, item_end = desc.item_bounds(item)
            self._item_start = int(item_start)
            auto = ex._automata(tls, w.job, node_id, si, item,
                                output_format=output_format)
            self.convert = (("yuv420", auto.vd.height, auto.vd.width)
                            if output_format == "yuv420" else None)
            self._hw = (auto.vd.height, auto.vd.width)

            # frame cache (engine/framecache.py): one plan for the
            # whole task's rows, pinned up front — the decode stream
            # then covers only the misses, and each chunk assembles as
            # a page gather + a staging copy of its fresh rows
            self._plan = None
            self._cache = None
            decode_rows = all_rows
            if use_cache:
                cache = _fc.cache()
                plan = cache.plan(
                    w.device, (ex._cache_db_key, desc.id), si["column"],
                    item, output_format, all_rows - item_start,
                    total_rows=item_end - item_start,
                    keyint=ex._keyint_of(si))
                _fc.attach_lease(w, plan.lease)
                self._plan = plan
                self._cache = cache
                self._miss = set((plan.miss_rows
                                  + item_start).tolist())
                decode_rows = plan.miss_rows + item_start

            def gen():
                for rr, fr in auto.stream_frames(
                        (decode_rows - item_start).tolist(),
                        packets_per_call=wp_est,
                        max_frames_per_yield=wp_est):
                    yield rr + item_start, fr

            self._gen = gen() if len(decode_rows) else iter(())

        def batch_for(self, rows: Sequence[int]) -> ColumnBatch:
            rows_arr = np.asarray(rows, np.int64)
            if self._plan is None:
                need = set(rows_arr.tolist()) - self._buf.keys()
            else:
                need = (set(rows_arr.tolist()) & self._miss) \
                    - self._buf.keys()
            t0 = time.time()
            decoded = 0
            while need:
                rr, fr = next(self._gen)  # StopIteration = decode bug
                for r, f in zip(rr.tolist(), fr):
                    self._buf[r] = f
                decoded += len(fr)
                need -= set(rr.tolist())
            if decoded:
                lbl = threading.current_thread().name
                _M_DECODED.labels(loader=lbl).inc(decoded)
                _M_DECODE_SECONDS.labels(loader=lbl).inc(time.time() - t0)
            if self._plan is None:
                data = np.stack([self._buf[int(r)] for r in rows_arr]) \
                    if len(rows_arr) else np.zeros((0,), np.uint8)
            else:
                # page-gather assembly: fresh (miss) rows of this chunk
                # feed page completion and stage once; resident rows
                # gather from the pinned pages on this task's chip
                fresh_g = sorted(set(rows_arr.tolist()) & self._miss)
                fresh_local = np.asarray(fresh_g, np.int64) \
                    - self._item_start
                fresh_data = (np.stack([self._buf[r] for r in fresh_g])
                              if fresh_g else np.zeros((0, 1), np.uint8))
                data = self._cache.assemble_rows(
                    self._plan, rows_arr - self._item_start,
                    fresh_local, fresh_data, hw=self._hw)
            keep_from = self._keep_from[self._chunk_i]
            self._chunk_i += 1
            for r in [r for r in self._buf if r < keep_from]:
                del self._buf[r]
            return ColumnBatch(rows_arr, data, convert=self.convert)

    def _iter_chunk_items(self, info: A.GraphInfo, w: TaskItem, tls):
        """Yield (plan, elements) per work-packet chunk of a streaming
        task, decoding incrementally and pre-staging device columns so
        the h2d of chunk k+1 rides under the compute of chunk k."""
        feeds: Dict[int, LocalExecutor._VideoFeed] = {}
        for nid in w.chunk_plans[0].source_rows:
            si = w.job.source_info[nid]
            if si.get("is_video") and "custom" not in si:
                fmt = ("yuv420" if self._yuv_device_wire(info, nid)
                       else "rgb24")
                feeds[nid] = self._VideoFeed(
                    self, w, tls, nid, si, w.chunk_plans, fmt,
                    use_cache=self._cache_eligible(info, nid))
        for plan in w.chunk_plans:
            elements: Dict[int, ColumnBatch] = {}
            t0 = time.time()
            with self.profiler.span("load", level=0, task=w.task_idx,
                                    job=w.job.job_idx,
                                    chunk=plan.output_range[0]):
                for nid, rows in plan.source_rows.items():
                    if nid in feeds:
                        elements[nid] = feeds[nid].batch_for(rows)
                    else:
                        elements[nid] = self._load_plain_source(
                            w, nid, [int(r) for r in rows])
                self._prestage_device_columns(info, w, elements=elements)
            _M_STAGE_SECONDS.labels(stage="load").inc(time.time() - t0)
            yield plan, elements

    def _chunk_put(self, w: TaskItem, item, stop) -> bool:
        while True:
            if (stop is not None and stop.is_set()) \
                    or w.chunk_abort.is_set():
                return False
            try:
                w.chunk_q.put(item, timeout=0.25)
                return True
            except queue.Full:
                pass

    def _produce_chunks(self, info: A.GraphInfo, w: TaskItem, tls,
                        stop=None) -> None:
        """Loader-side: decode chunks into the task's bounded queue; a
        consumer failure (chunk_abort) or pipeline stop unblocks us."""
        try:
            for item in self._iter_chunk_items(info, w, tls):
                if not self._chunk_put(w, item, stop):
                    return
            self._chunk_put(w, _CHUNK_DONE, stop)
        except Exception as e:  # noqa: BLE001 — surfaces on the consumer
            self._chunk_put(w, (_CHUNK_ERR, e), stop)
        finally:
            # aborted task (consumer failure/revoke, pipeline stop):
            # unpin frame-cache pages HERE — production has ended, so
            # no later append races this release; the consumer's own
            # release paths cover the normal completion order
            if (w.chunk_abort is not None and w.chunk_abort.is_set()) \
                    or (stop is not None and stop.is_set()):
                self._release_cache(w)

    def _consume_iter(self, info: A.GraphInfo, te, w: TaskItem,
                      chunk_iter, fb_tls) -> Dict[int, ColumnBatch]:
        """Execute (plan, elements) chunks from any iterator; merge
        per-sink results in row order (shared by the threaded queue
        consumer and the serial NO_PIPELINING path)."""
        if _faults.ACTIVE:
            _faults.inject("pipeline.eval",
                           detail=f"task={w.job.job_idx},{w.task_idx}")
        parts: Dict[int, List[ColumnBatch]] = {}
        n = 0
        for plan, elements in chunk_iter:
            res = self._execute_chunk(info, te, w, plan, elements, fb_tls)
            for sid, b in res.items():
                parts.setdefault(sid, []).append(b)
            n += 1
        self.profiler.count("stream_chunks", n)
        return {sid: concat_batches(lst) for sid, lst in parts.items()}

    def _consume_chunks(self, info: A.GraphInfo, te, w: TaskItem, fb_tls,
                        stop=None) -> Dict[int, ColumnBatch]:
        """Evaluator-side: execute chunks as they arrive over the
        producer queue.  Any failure aborts the producer."""

        def from_queue():
            while True:
                t0 = time.time()
                while True:
                    try:
                        item = w.chunk_q.get(timeout=0.25)
                        break
                    except queue.Empty:
                        if stop is not None and stop.is_set():
                            raise JobException(
                                "pipeline stopped during streaming task")
                waited = time.time() - t0
                _M_CHUNK_WAIT.inc(waited)
                if waited > 0.005:
                    # starvation attribution: time the evaluator spent
                    # waiting on the loader's chunk production (decode
                    # slower than compute shows up here, not as inflated
                    # kernel spans)
                    self.profiler.add_interval(
                        "evaluate:chunk_wait", t0, t0 + waited, level=1,
                        task=w.task_idx, job=w.job.job_idx)
                if item is _CHUNK_DONE:
                    return
                if isinstance(item, tuple) and item[0] is _CHUNK_ERR:
                    raise item[1]
                yield item

        try:
            return self._consume_iter(info, te, w, from_queue(), fb_tls)
        except BaseException:
            w.chunk_abort.set()
            raise

    def _execute_chunk(self, info: A.GraphInfo, te, w: TaskItem, plan,
                       elements, fb_tls) -> Dict[int, ColumnBatch]:
        from .evaluate import StateCarryMiss
        try:
            return te.execute_task(w.job.jr, plan, elements)
        except StateCarryMiss as e:
            _log.info("task (%d,%d) chunk %s: %s — re-running "
                      "self-contained", w.job.job_idx, w.task_idx,
                      plan.output_range, e)
            self.profiler.count("state_carry_miss")
            _tr.add_event("state_carry_miss", chunk=str(plan.output_range))
            plan2 = A.derive_task_streams(
                info, w.job.jr, plan.output_range,
                job_idx=w.job.job_idx, task_idx=w.task_idx)
            tmp = TaskItem(w.job, w.task_idx, plan.output_range,
                           plan=plan2, device=w.device)
            try:
                elements2 = self._load_sources(info, tmp, fb_tls)
                return te.execute_task(w.job.jr, plan2, elements2)
            finally:
                self._release_cache(tmp)

    def _evaluate_with_fallback(self, info: A.GraphInfo, te, w: TaskItem,
                                fb_tls):
        """Run a task; on a StateCarryMiss (the affinity chain's premise
        broke — reordering, failed predecessor, different instance)
        re-derive the self-contained plan, reload its sources, and run
        again.  Affinity is an optimization only."""
        if _faults.ACTIVE:
            _faults.inject("pipeline.eval",
                           detail=f"task={w.job.job_idx},{w.task_idx}")
        from .evaluate import StateCarryMiss
        try:
            return te.execute_task(w.job.jr, w.plan, w.elements)
        except StateCarryMiss as e:
            _log.info("task (%d,%d): %s — re-running self-contained",
                      w.job.job_idx, w.task_idx, e)
            self.profiler.count("state_carry_miss")
            _tr.add_event("state_carry_miss")
            w.plan = A.derive_task_streams(
                info, w.job.jr, w.output_range,
                job_idx=w.job.job_idx, task_idx=w.task_idx)
            w.elements = self._load_sources(info, w, fb_tls)
            self._prestage_device_columns(info, w)
            return te.execute_task(w.job.jr, w.plan, w.elements)

    def load_task(self, info: A.GraphInfo, w: TaskItem, tls) -> TaskItem:
        """The load stage: derive the task's row plan and read/decode its
        source elements (shared by the local pipeline and cluster
        workers)."""
        t0 = time.time()
        # success-only, like the evaluate/save stage counters: a failing
        # load must not read as the load stage racing ahead
        out = self._load_task(info, w, tls)
        _M_STAGE_SECONDS.labels(stage="load").inc(time.time() - t0)
        _M_STAGE_TASKS.labels(stage="load").inc()
        return out

    def _load_task(self, info: A.GraphInfo, w: TaskItem, tls) -> TaskItem:
        if _faults.ACTIVE:
            _faults.inject("pipeline.decode",
                           detail=f"task={w.job.job_idx},{w.task_idx}")
        with self.profiler.span("load", level=0, task=w.task_idx,
                                job=w.job.job_idx):
            chain = self._chains.get(w.job.job_idx)
            carry = chain.gate_plan(w.task_idx) if chain is not None \
                else None
            start, end = w.output_range
            wp = int(getattr(w.job.jr, "work_packet_size", 0) or 0)
            if self._stream_packets() and wp > 0 and (end - start) > wp:
                # Work-packet streaming (reference element cache +
                # feeder, evaluate_worker.h:207-218): the task's io
                # packet never materializes whole — per-chunk plans
                # drive an incremental decode -> h2d -> compute
                # pipeline; peak memory is a few chunks, and the h2d of
                # chunk k+1 rides under the compute of chunk k.
                plans = []
                cur = dict(carry) if carry else None
                for cs in range(start, end, wp):
                    p = A.derive_task_streams(
                        info, w.job.jr, (cs, min(cs + wp, end)),
                        job_idx=w.job.job_idx, task_idx=w.task_idx,
                        carry=cur)
                    if p.carry_watermarks:
                        cur = dict(cur or {})
                        cur.update(p.carry_watermarks)
                    plans.append(p)
                # a video source whose rows span multiple table items
                # keeps the whole-task path: per-item geometry may
                # differ, which the ragged concat handles and the
                # streaming feed's uniform batches would not
                streamable = True
                for nid in plans[0].source_rows:
                    si = w.job.source_info[nid]
                    if si.get("is_video") and "custom" not in si:
                        desc = si["table"]
                        items = {desc.item_of_row(int(r))
                                 for p in plans
                                 for r in p.source_rows[nid]}
                        if len(items) > 1:
                            streamable = False
                            break
                if streamable:
                    if chain is not None:
                        chain.planned(w.task_idx, cur or {})
                    w.chunk_plans = plans
                    w.plan = None
                    w.elements = None
                    w.chunk_q = queue.Queue(maxsize=2)
                    w.chunk_abort = threading.Event()
                    w.decode_rows = int(sum(
                        len(r) for p in plans
                        for r in p.source_rows.values()))
                    return w
            w.plan = A.derive_task_streams(
                info, w.job.jr, w.output_range,
                job_idx=w.job.job_idx, task_idx=w.task_idx, carry=carry)
            if chain is not None:
                chain.planned(w.task_idx, w.plan.carry_watermarks)
            # sharded gang members: rows owned by neighbor shards are
            # dropped from this member's decode plan BEFORE loading —
            # the loader and frame cache never see them — and restored
            # afterwards so downstream row math stays whole-plan; the
            # halo_fill hook then splices the exchanged boundary rows
            # into the loaded batches (engine/gang.py _make_halo_filler)
            restore: Dict[int, Any] = {}
            if w.halo_drop:
                for nid, drop in w.halo_drop.items():
                    rows = w.plan.source_rows.get(nid)
                    if rows is None or not len(drop):
                        continue
                    restore[nid] = rows
                    w.plan.source_rows[nid] = \
                        rows[~np.isin(rows, drop)]
            w.decode_rows = int(sum(
                len(r) for r in w.plan.source_rows.values()))
            w.elements = self._load_sources(info, w, tls)
            if restore:
                w.plan.source_rows.update(restore)
            if w.halo_fill is not None:
                w.halo_fill(info, w)
            self._prestage_device_columns(info, w)
        return w

    def _stream_packets(self) -> bool:
        import os
        if os.environ.get("SCANNER_TPU_STREAM_PACKETS", "1") \
                in ("0", "false"):
            return False
        return self._stream_opt

    def _prestage_device_columns(self, info: A.GraphInfo, w: TaskItem,
                                 elements: Optional[Dict[int, Any]] = None
                                 ) -> None:
        """Start the host->device transfer of device-bound source columns
        from the LOADER thread.  device_put is async: the copy proceeds
        while this loader decodes the next task and while the evaluator
        computes earlier tasks, so h2d overlaps decode instead of
        serializing at the front of the evaluate stage (PERF.md §3: h2d is
        a first-order term over the tunnel).  Only columns whose every
        first non-builtin consumer is a device kernel are staged — staging
        a host-kernel input would add a device->host round-trip.  The
        target is the chip of the instance this task was assigned to at
        enqueue time (w.device): staging to the default chip for a task
        that instance 3 will evaluate would force a cross-chip copy."""
        from .evaluate import _device_staging_enabled
        if not _device_staging_enabled():
            return
        cols = w.elements if elements is None else elements
        for nid, b in cols.items():
            if self._column_device_bound(info, nid) \
                    and isinstance(b.data, np.ndarray) \
                    and b.data.dtype != object:
                cols[nid] = b.to_device(w.device)

    def _yuv_device_wire(self, info: A.GraphInfo, node_id: int) -> bool:
        """Should this video column decode to YUV420 wire format?  Yes
        when every first non-builtin consumer is a device kernel (so the
        conversion runs once, on the accelerator) and the backend is an
        accelerator.  SCANNER_TPU_YUV_DEVICE=0 opts out; =force engages
        it on the CPU backend too (tests exercise the full path there)."""
        import os
        flag = os.environ.get("SCANNER_TPU_YUV_DEVICE", "1")
        if flag in ("0", "false"):
            return False
        from .evaluate import _accel_backend
        if flag != "force" and not _accel_backend():
            return False
        return self._column_device_bound(info, node_id)

    def _column_device_bound(self, info: A.GraphInfo, node_id: int) -> bool:
        with self._device_bound_lock:
            cache = self._device_bound_cache
            if cache.get("info") is not info:
                cache.clear()
                cache["info"] = info
            if node_id in cache:
                return cache[node_id]
        by_id = {n.id: n for n in info.ops}
        devices: List[bool] = []
        seen = set()
        frontier = [node_id]
        while frontier:
            nid = frontier.pop()
            if nid in seen:
                continue
            seen.add(nid)
            for cid in info.consumers.get(nid, []):
                c = by_id[cid]
                if c.name == O.OUTPUT_OP:
                    devices.append(False)  # sink fetches to host
                elif c.is_builtin:
                    frontier.append(cid)   # gathers run wherever data is
                else:
                    devices.append(
                        c.effective_device() == DeviceType.TPU)
        res = bool(devices) and all(devices)
        with self._device_bound_lock:
            if self._device_bound_cache.get("info") is info:
                self._device_bound_cache[node_id] = res
        return res

    def _load_sources(self, info: A.GraphInfo, w: TaskItem,
                      tls) -> Dict[int, ColumnBatch]:
        """Read/decode exactly the rows the task needs.  Video sources
        arrive as ONE contiguous (N, H, W, 3) batch straight from the
        decoder — the zero-copy head of the batched data path."""
        out: Dict[int, ColumnBatch] = {}
        for node_id, rows in w.plan.source_rows.items():
            si = w.job.source_info[node_id]
            rows_arr = np.asarray(rows, np.int64)
            rows_l = [int(r) for r in rows]
            if "custom" in si or not si["is_video"]:
                out[node_id] = self._load_plain_source(w, node_id, rows_l)
            elif si["is_video"]:
                # rows are global; multi-item video tables (job outputs)
                # hold one independently-decodable item per task
                desc = si["table"]
                # Device-bound frame columns decode to planar YUV420 and
                # convert to RGB ON the accelerator (kernels/color.py):
                # 1.5 B/px instead of 3 B/px over the host->device link,
                # the first-order term of device pipelines (PERF.md §1;
                # the reference shipped NV12 and converted on-GPU,
                # util/image.cu:22).  SCANNER_TPU_YUV_DEVICE=0 opts out.
                fmt = ("yuv420" if self._yuv_device_wire(info, node_id)
                       else "rgb24")
                # paged frame cache (engine/framecache.py): rows already
                # resident in HBM pages on this task's chip skip decode
                # AND the np->device copy; only miss ranges decode
                cached = self._load_video_cached(info, w, node_id, si,
                                                 rows_l, fmt, tls)
                if cached is not None:
                    out[node_id] = cached
                    continue
                by_item: Dict[int, List[int]] = {}
                for r in rows_l:
                    it = desc.item_of_row(r)
                    start, _ = desc.item_bounds(it)
                    by_item.setdefault(it, []).append(r - start)
                parts: List[ColumnBatch] = []
                for it, local in sorted(by_item.items()):
                    start, _ = desc.item_bounds(it)
                    auto = self._automata(tls, w.job, node_id, si, it,
                                          output_format=fmt)
                    t0 = time.time()
                    frames = auto.get_frames(local)
                    lbl = threading.current_thread().name
                    _M_DECODED.labels(loader=lbl).inc(len(local))
                    _M_DECODE_SECONDS.labels(loader=lbl).inc(
                        time.time() - t0)
                    # convert mark carries THIS item's geometry (items of
                    # one table may differ); mixed-geometry concat falls
                    # back to host conversion in concat_batches
                    convert = (("yuv420", auto.vd.height, auto.vd.width)
                               if fmt == "yuv420" else None)
                    parts.append(ColumnBatch(
                        np.asarray(local, np.int64) + start, frames,
                        convert=convert))
                out[node_id] = concat_batches(parts)
        return out

    def _load_plain_source(self, w: TaskItem, node_id: int,
                           rows_l: List[int]) -> ColumnBatch:
        """Non-video source rows: custom-storage reads or column loads
        (shared by the whole-task and per-chunk streaming loaders)."""
        si = w.job.source_info[node_id]
        rows_arr = np.asarray(rows_l, np.int64)
        if "custom" in si:
            vals = si["custom"].storage.read_rows(si["custom"], rows_l)
            return ColumnBatch.from_elements(rows_arr, vals)
        from ..storage.streams import decode_element
        desc = si["table"]
        vals = list(self.db.load_column(
            desc.id, si["column"], rows=rows_l,
            sparsity_threshold=w.job.sparsity_threshold))
        codec = si.get("codec", "raw")
        return ColumnBatch.from_elements(
            rows_arr, [decode_element(v, codec) for v in vals])

    def _cache_eligible(self, info: A.GraphInfo, node_id: int) -> bool:
        """Frame-cache eligibility for one video column: the cache is
        an HBM pool, so only device-staged columns qualify — and only
        when the kill switch is up (SCANNER_TPU_FRAME_CACHE=0 /
        [perf] frame_cache_enabled)."""
        from .evaluate import _device_staging_enabled
        return _fc.enabled() and _device_staging_enabled() \
            and self._column_device_bound(info, node_id)

    @staticmethod
    def _keyint_of(si) -> int:
        """Keyframe-interval estimate for page sizing (pages should map
        onto GOP-decodable units); 0 = unknown."""
        vd = si.get("video_meta")
        ki = getattr(vd, "keyframe_indices", None) if vd is not None \
            else None
        if ki is not None and len(ki) > 1:
            return int(np.median(np.diff(np.asarray(ki, np.int64))))
        return 0

    def _load_video_cached(self, info: A.GraphInfo, w: TaskItem,
                           node_id: int, si, rows_l: List[int], fmt: str,
                           tls) -> Optional[ColumnBatch]:
        """The cache-consulting flavor of the whole-task video load:
        plan (pin resident pages on this task's chip), decode only the
        miss rows, offer them toward page completion, and assemble the
        task's column as a page-table gather.  None = ineligible or
        bypassed — the caller runs the direct decode+stage path."""
        if not self._cache_eligible(info, node_id) or not rows_l:
            return None
        desc = si["table"]
        items = {desc.item_of_row(int(r)) for r in rows_l}
        if len(items) != 1:
            # per-item geometry may differ (the ragged-concat path);
            # pages are per-item, so a multi-item task stays direct
            return None
        item = items.pop()
        start, end = desc.item_bounds(item)
        local = np.asarray(rows_l, np.int64) - start
        cache = _fc.cache()
        plan = cache.plan(w.device, (self._cache_db_key, desc.id),
                          si["column"], item, fmt, local,
                          total_rows=end - start,
                          keyint=self._keyint_of(si))
        # pin BEFORE decoding: a decode failure routes through
        # task_failed -> _release_cache, and the finalizer backstops
        _fc.attach_lease(w, plan.lease)
        miss = plan.miss_rows
        hw = plan.hw
        if len(miss):
            auto = self._automata(tls, w.job, node_id, si, item,
                                  output_format=fmt)
            t0 = time.time()
            frames = auto.get_frames(miss.tolist())
            lbl = threading.current_thread().name
            _M_DECODED.labels(loader=lbl).inc(len(miss))
            _M_DECODE_SECONDS.labels(loader=lbl).inc(time.time() - t0)
            hw = (auto.vd.height, auto.vd.width)
        else:
            frames = np.zeros((0, 1), np.uint8)
        if fmt == "yuv420" and not (hw and hw[0]):
            return None  # no geometry for the convert mark: bypass
        try:
            data = cache.assemble(plan, miss, frames, hw=hw)
        except _fc.CacheBypass:
            # falling back here re-decodes the miss rows on the direct
            # path (double decode for this one task).  Acceptable: a
            # bypass after plan() requires a pinned hit row to vanish,
            # which pinning exists to prevent — this is a correctness
            # backstop, not a path with a cost budget.
            return None
        convert = (("yuv420", hw[0], hw[1]) if fmt == "yuv420" else None)
        return ColumnBatch(np.asarray(rows_l, np.int64), data,
                           convert=convert)

    def _release_cache(self, w: TaskItem) -> None:
        """Unpin the task's frame-cache pages (evaluation is done with
        them, or the task failed/was revoked).  Idempotent; leases a
        dropped TaskItem never reaches release on are backstopped by
        the finalizer attach_lease installed."""
        leases, w.cache_leases = w.cache_leases, None
        for lease in leases or ():
            lease.release()

    def _automata(self, tls, job: JobContext, node_id: int, si,
                  item: int = 0, output_format: str = "rgb24"):
        cache = getattr(tls, "automata", None)
        if cache is None:
            cache = {}
            tls.automata = cache
        key = (job.job_idx, node_id, item, output_format)
        if key not in cache:
            from ..video.automata import DecoderAutomata
            desc = si["table"]
            if item == 0:
                vd = si["video_meta"]
            else:
                vd = md.VideoDescriptor.deserialize(self.db.backend.read(
                    md.video_meta_path(desc.id, si["column"], item)))
            cache[key] = DecoderAutomata(
                self.db.backend, vd,
                md.column_item_path(desc.id, si["column"], item),
                n_threads=self.decoder_threads,
                output_format=output_format)
        return cache[key]

    def _save_task(self, info: A.GraphInfo, w: TaskItem) -> None:
        """Encode + write one item per sink (reference save_worker.cpp +
        PostEvaluateWorker video encode, evaluate_worker.cpp:1373-1560)."""
        if _faults.ACTIVE:
            _faults.inject("pipeline.save",
                           detail=f"task={w.job.job_idx},{w.task_idx}")
        start, end = w.output_range
        for sink in info.sinks:
            if sink.id in w.job.custom_sinks:
                stream = w.job.custom_sinks[sink.id]
                stream.storage.write_item(
                    stream, start,
                    self._sink_rows(w.results[sink.id], start, end))
                continue
            if sink.id not in w.job.sink_tables:
                continue
            desc, col_name, codec, enc_opts = w.job.sink_tables[sink.id]
            # the single device->host fetch of the batched data path
            rows = self._sink_rows(w.results[sink.id], start, end)
            item_idx = w.task_idx
            if codec == "frame":
                mode = "video" if self._is_encodable(rows) else "pickle"
                with w.job.sink_mode_lock:
                    prev = w.job.sink_modes.get(sink.id)
                    if prev is None:
                        # cross-worker guard: exactly one writer (across all
                        # processes) creates the durable marker; everyone
                        # else reads the winner's mode (distributed savers
                        # share no process state)
                        marker = f"{md.table_dir(desc.id)}/.{col_name}.mode"
                        if self.db.backend.write_exclusive(
                                marker, mode.encode()):
                            prev = mode
                        else:
                            prev = self.db.backend.read(marker).decode()
                        w.job.sink_modes[sink.id] = prev
                    if prev != mode:
                        raise JobException(
                            f"{desc.name}: mixed frame output types across "
                            f"tasks ({prev} vs {mode}); kernels must "
                            f"produce a consistent frame dtype")
                    if mode == "pickle":
                        self._demote_video_column(desc)
                if mode == "video":
                    self._write_video_item(w.job, desc, col_name, item_idx,
                                           rows, enc_opts)
                else:
                    # non-uint8/RGB frame data (e.g. float32 flow fields):
                    # the reference stores these as RAW-format video
                    # columns; here the column degrades to pickled arrays
                    import pickle
                    IT.write_item(
                        self.db.backend,
                        md.column_item_path(desc.id, col_name, item_idx),
                        [e if isinstance(e, NullElement)
                         else pickle.dumps(np.asarray(e),
                                           protocol=pickle.HIGHEST_PROTOCOL)
                         for e in rows])
            else:
                blobs = []
                for e in rows:
                    if isinstance(e, NullElement):
                        blobs.append(e)
                    elif codec == "raw":
                        if not isinstance(e, (bytes, bytearray, memoryview)):
                            raise JobException(
                                f"{desc.name}: raw column got "
                                f"{type(e).__name__}")
                        blobs.append(bytes(e))
                    else:
                        import pickle
                        blobs.append(pickle.dumps(
                            e, protocol=pickle.HIGHEST_PROTOCOL))
                IT.write_item(self.db.backend,
                              md.column_item_path(desc.id, col_name,
                                                  item_idx), blobs)

    @staticmethod
    def _async_sink_fetch_enabled() -> bool:
        """SCANNER_TPU_ASYNC_SINK_FETCH=0 opts out of starting sink
        device->host copies at eval-done (the fetch then blocks in the
        saver, the pre-affinity behavior; the ordering test A/Bs it)."""
        import os
        return os.environ.get("SCANNER_TPU_ASYNC_SINK_FETCH", "1") \
            not in ("0", "false")

    def _prefetch_results(self, w: TaskItem) -> None:
        """Kick off the async device->host copy of every sink batch the
        moment evaluation finishes — hung off the TaskItem before it
        enters save_q, so task k's ~180 ms d2h latency rides under task
        k+1's evaluation instead of serializing inside the saver."""
        if not w.results or not self._async_sink_fetch_enabled():
            return
        for b in w.results.values():
            if isinstance(b, ColumnBatch):
                b.prefetch_host()

    @staticmethod
    def _sink_rows(batch, start: int, end: int) -> List[Any]:
        """Materialize a sink ColumnBatch's rows [start, end) as host
        elements (array rows become views).  The whole batch is fetched
        FIRST — completing the async copy _prefetch_results started at
        eval-done (a device-side slice would be a fresh array the
        prefetch never covered) — then the contiguous range takes
        ColumnBatch.take_range's direct-slice fast path on host."""
        return batch.to_host().take_range(start, end).elements()

    @staticmethod
    def _is_encodable(rows: List[Any]) -> bool:
        """True when the item is H.264-encodable (uint8 RGB).  Null rows in
        an otherwise-encodable item raise inside _write_video_item, matching
        the reference where video columns cannot hold nulls."""
        saw_frame = False
        for e in rows:
            if isinstance(e, NullElement):
                continue
            a = np.asarray(e)
            if a.dtype != np.uint8 or a.ndim != 3 or a.shape[2] != 3:
                return False
            saw_frame = True
        return saw_frame

    def _demote_video_column(self, desc: md.TableDescriptor) -> None:
        col = desc.columns[0]
        already = (col.type == md.ColumnType.BYTES and col.codec == "pickle")
        if not already:
            col.type = md.ColumnType.BYTES
            col.codec = "pickle"
            self.db.write_table_descriptor(desc)

    def _write_video_item(self, job: JobContext, desc: md.TableDescriptor,
                          col_name: str, item_idx: int, rows: List[Any],
                          enc_opts: Dict) -> None:
        from ..video.lib import Encoder
        frames = []
        for e in rows:
            if isinstance(e, NullElement):
                raise JobException(
                    f"{desc.name}: video output cannot store null rows; "
                    f"use a blob column")
            a = np.asarray(e)
            if a.dtype != np.uint8 or a.ndim != 3 or a.shape[2] != 3:
                raise JobException(
                    f"{desc.name}: video output requires uint8 HxWx3 "
                    f"frames, got {a.dtype} {a.shape}")
            frames.append(a)
        h, w_ = frames[0].shape[:2]
        keyint = int(enc_opts.get("keyint", 16))
        enc = Encoder(w_, h, fps=job.fps or 30.0, codec="libx264",
                      bitrate=int(enc_opts.get("bitrate", 0)),
                      crf=int(enc_opts.get("crf", 20)), keyint=keyint)
        try:
            for f in frames:
                enc.feed(f)
            enc.flush()
            data, sizes, keys, pts, dts = enc.take_packets()
            vd = md.VideoDescriptor(
                width=w_, height=h, fps=job.fps or 30.0,
                num_frames=len(frames), codec="h264",
                extradata=enc.extradata,
                sample_offsets=np.concatenate(
                    [[0], np.cumsum(sizes[:-1])]).astype(np.uint64)
                if len(sizes) else np.zeros(0, np.uint64),
                sample_sizes=sizes.astype(np.uint64),
                keyframe_indices=np.nonzero(keys)[0].astype(np.int64),
                sample_pts=pts, sample_dts=dts,
                tb_num=enc.fps_den, tb_den=enc.fps_num)
            self.db.backend.write(
                md.column_item_path(desc.id, col_name, item_idx), data)
            self.db.backend.write(
                md.video_meta_path(desc.id, col_name, item_idx),
                vd.serialize())
        finally:
            enc.close()
