"""Paged per-device HBM frame cache with cross-task reuse.

Scanner's promise is minimal-decode scheduling, yet until this module
every task re-paid decode + PCIe for bytes already sitting in HBM on the
right chip: overlapping stencil windows re-decode their back-reach rows,
Gather samplings re-decode the hot clip, and a second pipeline over the
same table starts from scratch.  This is the fix — a per-device paged
frame pool in the spirit of Ragged Paged Attention's paged KV cache
(PAPERS.md): decoded frames live in fixed-size, keyframe-aligned HBM
pages keyed by ``(table, column, item, wire format, page)`` *per
device*, the loader consults the pool before planning decode and only
decodes the miss ranges, and staging becomes a page-table gather on the
task's assigned chip instead of a fresh np→device copy.

Design points:

  * **Pages are GOP-decodable units.**  The page size is a multiple of
    the stream's keyframe interval (auto-derived; ``[perf]
    frame_cache_page_frames`` pins it), aligned to the item start, so a
    page never needs packets outside its own keyframe runs.  The tail
    page of an item is short — fixed-size with a ragged top rung, like
    the bucket ladder.
  * **No extra decode, no extra h2d.**  The pool never widens a task's
    decode, and page fills ride the very device blocks the task stages
    for itself: a completed page is an ON-DEVICE concatenate of
    retained staged blocks (``_fill`` buffers, bounded LRU), so a cold
    cache-on run ships exactly the bytes a cache-off run would.  Dense
    tasks complete their pages in one pass; sparse Gather samplings
    rarely complete pages but *hit* the pages dense traffic left hot.
  * **LRU with in-flight pinning.**  ``plan()`` pins every page a task
    will gather from; the executor releases the lease when evaluation
    finishes (with a ``weakref.finalize`` backstop on the TaskItem), so
    eviction can never "free" bytes an in-flight dispatch still
    references — the capacity accounting stays honest.  Eviction takes
    the oldest unpinned page; an all-pinned pool may transiently
    overshoot its target rather than corrupt a task.
  * **Byte-accurate accounting.**  Every page registers in the PR 7
    allocation ledger (``memstats.track_array``, kind=``cache``), page
    staging counts into the same ``scanner_tpu_h2d_*`` series direct
    staging does (so a cache-on/off A/B compares like for like), and a
    firing ``hbm_pressure`` alert shrinks the capacity target and
    evicts down *before* OOM strikes a task
    (``scanner_tpu_framecache_pressure_shrinks_total``).

``SCANNER_TPU_FRAME_CACHE=0`` is the kill switch / A/B lever;
``SCANNER_TPU_FRAME_CACHE_MB`` overrides the per-device capacity.  The
``[perf] frame_cache_*`` config keys carry deployment defaults (see
docs/guide.md); docs/observability.md §Frame cache catalogs the series
(scanner-check SC310 pins both contracts).
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..util import faults as _faults
from ..util import memstats as _ms
from ..util import metrics as _mx
from ..util import tracing as _tracing
from ..util.log import get_logger

_log = get_logger("framecache")

# the SC310 contract: this tuple, the series registered below, and the
# marker-delimited table in docs/observability.md §Frame cache may not
# drift (all pairings, both directions)
FRAMECACHE_SERIES = (
    "scanner_tpu_framecache_hits_total",
    "scanner_tpu_framecache_misses_total",
    "scanner_tpu_framecache_inserts_total",
    "scanner_tpu_framecache_evictions_total",
    "scanner_tpu_framecache_pinned_bytes",
    "scanner_tpu_framecache_live_bytes",
    "scanner_tpu_framecache_capacity_bytes",
    "scanner_tpu_framecache_pressure_shrinks_total",
)

# the [perf] frame_cache_* config keys config.default_config() must
# declare — exactly these (scanner-check SC310, both directions)
CONFIG_KEYS = ("frame_cache_enabled", "frame_cache_mb",
               "frame_cache_page_frames")

_M_HITS = _mx.registry().counter(
    "scanner_tpu_framecache_hits_total",
    "Frames served from resident frame-cache pages instead of decode + "
    "host->device staging, per device.",
    labels=["device"])
_M_MISSES = _mx.registry().counter(
    "scanner_tpu_framecache_misses_total",
    "Frames a cache-consulting load still had to decode (page absent "
    "or not yet filled), per device.",
    labels=["device"])
_M_INSERTS = _mx.registry().counter(
    "scanner_tpu_framecache_inserts_total",
    "Frame-cache pages staged to device and inserted, per device.",
    labels=["device"])
_M_EVICTIONS = _mx.registry().counter(
    "scanner_tpu_framecache_evictions_total",
    "Frame-cache pages evicted (LRU capacity eviction or pressure "
    "shrink), per device.",
    labels=["device"])
_M_PINNED = _mx.registry().gauge(
    "scanner_tpu_framecache_pinned_bytes",
    "Bytes of frame-cache pages currently pinned by in-flight tasks "
    "(ineligible for eviction), per device.",
    labels=["device"])
_M_LIVE = _mx.registry().gauge(
    "scanner_tpu_framecache_live_bytes",
    "Bytes of resident frame-cache pages, per device (also visible as "
    "ledger kind=cache in the scanner_tpu_ledger_* series).",
    labels=["device"])
_M_CAPACITY = _mx.registry().gauge(
    "scanner_tpu_framecache_capacity_bytes",
    "Current frame-cache capacity target per device (config/env "
    "default, lowered by hbm_pressure shrinks).",
    labels=["device"])
_M_SHRINKS = _mx.registry().counter(
    "scanner_tpu_framecache_pressure_shrinks_total",
    "Capacity-target shrinks triggered by a firing hbm_pressure alert "
    "(the auto-remediation seed: evict down before OOM strikes a "
    "task), per device.",
    labels=["device"])


# -- knobs ------------------------------------------------------------------

# same env semantics as SCANNER_TPU_MEMSTATS (one parser, no drift);
# SCANNER_TPU_FRAME_CACHE=0 is the A/B kill switch
_ENABLED = _tracing._env_on("SCANNER_TPU_FRAME_CACHE")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Programmatic override ([perf] frame_cache_enabled config key,
    tests, bench A/B); the SCANNER_TPU_FRAME_CACHE env var is read at
    import and wins when set (call sites guard on it)."""
    global _ENABLED
    _ENABLED = bool(on)


def _env_capacity_mb() -> Optional[int]:
    v = os.environ.get("SCANNER_TPU_FRAME_CACHE_MB", "")
    try:
        return max(1, int(v)) if v else None
    except ValueError:
        return None


DEFAULT_CAPACITY_MB = 256
_capacity_mb = _env_capacity_mb() or DEFAULT_CAPACITY_MB
# floor the pressure shrink can't go below: a page or two must always
# fit or the cache thrashes pointlessly at zero
MIN_CAPACITY_BYTES = 8 << 20


def set_capacity_mb(mb: int) -> None:
    """[perf] frame_cache_mb config wiring; the SCANNER_TPU_FRAME_CACHE_MB
    env var (read at import) wins when set.  An explicit reconfigure
    also clears persisted pressure-shrink targets — the operator's
    documented way to re-arm a device hbm_pressure capped."""
    global _capacity_mb
    if _env_capacity_mb() is None:
        _capacity_mb = max(1, int(mb))
        if _CACHE is not None:
            with _CACHE._lock:
                _CACHE._target.clear()


# 0 = auto: the smallest multiple of the stream's keyframe interval
# >= _PAGE_BASE frames, so pages land on GOP boundaries
_PAGE_BASE = 32
_page_frames_cfg = 0


def set_page_frames(n: int) -> None:
    """[perf] frame_cache_page_frames config wiring (0 = auto)."""
    global _page_frames_cfg
    _page_frames_cfg = max(0, int(n))


# host-side fill buffers: pending (incomplete) pages retained at most
_MAX_FILL_PAGES = 64


# mesh-aware cache identity for sharded gang members (engine/gang.py):
# a member evaluating only rows [lo, hi) of every task tags its pages
# with its shard identity, so page keys are scoped under
# (host-shard, device) — a re-formed gang at a different num_processes
# (whose shard boundaries moved) can never gather a stale page built
# under the old layout, and residency per member is 1/N by construction
# (the shard plan only ever touches shard rows).  None = unsharded
# (the default single-host / replicated identity).
_HOST_SHARD: Optional[str] = None


def set_host_shard(tag: Optional[str]) -> None:
    """Scope this process's cache pages under a shard identity (sharded
    gang member children call this once before evaluating; pass None to
    clear)."""
    global _HOST_SHARD
    _HOST_SHARD = str(tag) if tag else None


def host_shard() -> Optional[str]:
    return _HOST_SHARD


# cache identity for a Database backend: (root, process-unique seq).
# The seq — minted once per backend OBJECT via a weak map — is what
# makes the key collision-proof: a database deleted and re-created at
# the same root restarts table ids at 0, and `id()` alone can be
# reused after collection.  The cost is that two Database objects over
# the same root do not share pages (one worker = one Database in
# practice).
_DB_KEYS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_DB_SEQ = [0]
_DB_KEY_LOCK = threading.Lock()


def db_cache_key(backend: Any) -> Tuple[Any, int]:
    root = getattr(backend, "root", None) or "mem"
    try:
        with _DB_KEY_LOCK:
            key = _DB_KEYS.get(backend)
            if key is None:
                _DB_SEQ[0] += 1
                key = (root, _DB_SEQ[0])
                _DB_KEYS[backend] = key
            return key
    except TypeError:  # un-weakref-able backend: fall back to identity
        return (root, id(backend))


def _runs(seq: List[int]):
    """Yield (lo, hi) index ranges of `seq` over which the VALUES are
    consecutive integers (maximal runs)."""
    i = 0
    while i < len(seq):
        j = i + 1
        while j < len(seq) and seq[j] == seq[j - 1] + 1:
            j += 1
        yield i, j
        i = j


class CacheBypass(Exception):
    """The cache cannot serve this request (mixed page geometry after a
    table rewrite mid-flight, jax unavailable); callers fall back to the
    direct decode + staging path — the cache is an optimization only."""


# -- internal structures ----------------------------------------------------

# a page's identity:
# (device label, table key, column, item, fmt, page idx) — the table
# key is opaque (the executor passes (db root, table id))
PageKey = Tuple[Any, ...]


class _Page:
    __slots__ = ("key", "data", "start", "n", "nbytes", "pins", "hw")

    def __init__(self, key: PageKey, data: Any, start: int, n: int,
                 hw: Tuple[int, int]):
        self.key = key
        self.data = data            # jax array (n, ...) wire-format rows
        self.start = start          # first item-local row of the page
        self.n = n                  # rows resident (== page size or tail)
        self.nbytes = int(getattr(data, "nbytes", 0))
        self.pins = 0
        self.hw = hw                # decoded (height, width) for convert


class Lease:
    """Pins a set of pages for the life of one task's dispatch; released
    by the executor when evaluation finishes (idempotent, thread-safe —
    a weakref.finalize on the owning TaskItem is the backstop for
    aborted pipelines)."""

    __slots__ = ("_cache", "_pages", "_released")

    def __init__(self, cache: "FrameCache"):
        self._cache = cache
        self._pages: List[_Page] = []
        self._released = False

    def release(self) -> None:
        self._cache._release_lease(self)


class Plan:
    """One cache consultation: which of a task's rows are resident (and
    now pinned), which must still be decoded."""

    __slots__ = ("device", "dev", "skey", "page_frames", "rows",
                 "total_rows", "hit_mask", "miss_rows", "lease", "hw")

    def __init__(self, device: Any, dev: str, skey: Tuple, page_frames: int,
                 rows: np.ndarray, total_rows: int, hit_mask: np.ndarray,
                 lease: Lease, hw: Optional[Tuple[int, int]]):
        self.device = device        # jax device (or None = default)
        self.dev = dev              # its label
        self.skey = skey            # (table_id, column, item, fmt)
        self.page_frames = page_frames
        self.rows = rows            # item-local, sorted
        self.total_rows = total_rows
        self.hit_mask = hit_mask    # bool per row
        self.miss_rows = rows[~hit_mask]
        self.lease = lease
        self.hw = hw                # (h, w) from a hit page, if any


class FrameCache:
    """The per-process pool.  One instance (``cache()``); per-device
    state inside, so chip 1's tasks can never gather chip 0's pages —
    the page key leads with the device label."""

    def __init__(self):
        # RLock, not Lock: lease release runs from weakref finalizers
        # (the TaskItem backstop), which the cyclic GC may fire at any
        # allocation point — including inside a locked plan/offer on
        # the SAME thread.  Lock-order rule (the memstats ledger's):
        # the finalizer path (_release_lease) touches ONLY this lock
        # and plain dict/int work, and NOTHING acquires a metrics
        # family/child lock while holding this one (_ensure_gauges and
        # every metric inc run strictly outside it).
        self._lock = threading.RLock()
        self._pages: "OrderedDict[PageKey, _Page]" = OrderedDict()
        # (dev, skey, page_idx) -> {local_row: (device block, offset)} —
        # pages complete from the DEVICE blocks assemble already staged
        # (an on-device concatenate), so filling a page never re-pays
        # h2d for rows the task shipped anyway
        self._fill: "OrderedDict[Tuple, Dict[int, Tuple[Any, int]]]" = \
            OrderedDict()
        # fill-fragment byte accounting: fragments are HBM too, so they
        # bill against the capacity target and evict (oldest first,
        # before any page — an incomplete page is the cheapest victim)
        self._fill_nbytes: Dict[Tuple, int] = {}
        self._fill_dev: Dict[str, int] = {}
        self._page_frames: Dict[Tuple, int] = {}   # per skey
        self._live: Dict[str, int] = {}
        self._pinned: Dict[str, int] = {}
        self._target: Dict[str, int] = {}          # capacity per device
        self._hits: Dict[str, int] = {}
        self._misses: Dict[str, int] = {}
        self._evictions: Dict[str, int] = {}
        self._inserts: Dict[str, int] = {}
        self._shrinks: Dict[str, int] = {}
        self._gauged: set = set()

    # -- gauges (scrape-time samplers, like the memstats ledger) --------

    def _ensure_gauges(self, dev: str) -> None:
        # only the process singleton may bind the process-global
        # gauges: a private instance (tests) would otherwise hijack the
        # samplers — and be kept alive forever by their closures
        if self is not _CACHE or dev in self._gauged:
            return
        self._gauged.add(dev)
        _M_LIVE.labels(device=dev).set_function(
            lambda d=dev: float(self._live.get(d, 0)))
        _M_PINNED.labels(device=dev).set_function(
            lambda d=dev: float(self._pinned.get(d, 0)))
        _M_CAPACITY.labels(device=dev).set_function(
            lambda d=dev: float(self._capacity(d)))

    def _capacity(self, dev: str) -> int:
        return self._target.get(dev, _capacity_mb << 20)

    # -- page math ------------------------------------------------------

    def _resolve_page_frames(self, skey: Tuple, keyint: int) -> int:
        pf = self._page_frames.get(skey)
        if pf is not None:
            return pf
        if _page_frames_cfg > 0:
            pf = _page_frames_cfg
        elif keyint and keyint > 1:
            # smallest keyint multiple >= _PAGE_BASE: pages are whole
            # GOPs, so filling one never needs foreign packets
            pf = keyint * max(1, -(-_PAGE_BASE // keyint))
        else:
            pf = _PAGE_BASE
        self._page_frames[skey] = pf
        return pf

    @staticmethod
    def _page_len(start: int, page_frames: int, total_rows: int) -> int:
        return min(page_frames, total_rows - start)

    # -- the loader-facing API ------------------------------------------

    def plan(self, device: Any, table: Any, column: str, item: int,
             fmt: str, rows: np.ndarray, total_rows: int,
             keyint: int = 0) -> Plan:
        """Consult the pool for a task's item-local `rows` on `device`:
        pins every resident page that covers one of them, counts
        hit/miss telemetry, and returns the plan whose ``miss_rows``
        the loader still decodes.  `table` is an opaque hashable source
        identity — the executor passes (db root, table id): ids are
        per-database and restart at 0, so the id alone would alias
        same-shaped tables of different databases in one process;
        recreated tables mint fresh ids, which is the staleness story."""
        dev = _ms.device_label(device)
        # page identity is (host-shard, device, table, column, item,
        # fmt, page): sharded gang members never share pages across
        # shard layouts (set_host_shard above)
        skey = (_HOST_SHARD, table, column, int(item), fmt) \
            if _HOST_SHARD else (table, column, int(item), fmt)
        rows = np.asarray(rows, np.int64)
        lease = Lease(self)
        hit = np.zeros(len(rows), bool)
        hw: Optional[Tuple[int, int]] = None
        with self._lock:
            pf = self._resolve_page_frames(skey, keyint)
            pinned: Dict[int, _Page] = {}
            for i, r in enumerate(rows.tolist()):
                pidx = r // pf
                page = pinned.get(pidx)
                if page is None:
                    key = (dev,) + skey + (pidx,)
                    page = self._pages.get(key)
                    if page is None:
                        continue
                    self._pages.move_to_end(key)
                    self._pin_locked(page, lease)
                    pinned[pidx] = page
                    hw = hw or page.hw
                # both bounds: a surviving page built under a DIFFERENT
                # page size (clear() keeps pinned pages but re-resolves
                # sizes) must never match a row below its start — a
                # negative gather index would wrap to the wrong frame
                if 0 <= r - page.start < page.n:
                    hit[i] = True
            n_hit = int(hit.sum())
            n_miss = len(rows) - n_hit
            self._hits[dev] = self._hits.get(dev, 0) + n_hit
            self._misses[dev] = self._misses.get(dev, 0) + n_miss
        # metric work strictly OUTSIDE the pool lock (lock-order rule
        # at self._lock)
        self._ensure_gauges(dev)
        if n_hit:
            _M_HITS.labels(device=dev).inc(n_hit)
            _tracing.add_event("cache.hit", device=dev, rows=n_hit)
        if n_miss:
            _M_MISSES.labels(device=dev).inc(n_miss)
            _tracing.add_event("cache.miss", device=dev, rows=n_miss)
        return Plan(device, dev, skey, pf, rows, int(total_rows), hit,
                    lease, hw)

    def _offer_block(self, plan: Plan, seg_rows: np.ndarray, block: Any,
                     hw: Optional[Tuple[int, int]]) -> None:
        """Feed one freshly staged device block (block[i] holds row
        seg_rows[i]) toward page completion.  A page whose every row is
        now covered builds by an ON-DEVICE concatenate of the retained
        blocks — filling the pool never re-pays h2d for rows the task
        staged anyway; incomplete pages buffer block references
        (bounded LRU) until later tasks complete them.  Best-effort:
        a failed page build only loses caching, never the task."""
        if not len(seg_rows):
            return
        pf = plan.page_frames
        # phase 1 (locked): which rows does each touched page still need
        claims: List[Tuple[Tuple, int, int, int, List[int]]] = []
        with self._lock:
            for pidx in np.unique(seg_rows // pf).tolist():
                start = int(pidx) * pf
                plen = self._page_len(start, pf, plan.total_rows)
                if plen <= 0:
                    continue
                fkey = (plan.dev,) + plan.skey + (int(pidx),)
                if fkey in self._pages:
                    continue
                have = self._fill.get(fkey) or ()
                sel = [pos for pos in np.flatnonzero(
                    (seg_rows >= start)
                    & (seg_rows < start + plen)).tolist()
                    if int(seg_rows[pos]) not in have]
                if sel:
                    claims.append((fkey, int(pidx), start, plen, sel))
        if not claims:
            return
        # phase 2 (UNLOCKED): the device fragment copies — they block on
        # the backend, and holding the process-wide pool lock across
        # them would stall every other loader's cache consultation.
        # Copying out of the task's block matters: retaining the block
        # itself would pin the whole task batch in HBM until the page
        # completes, and jnp.array forces a distinct buffer (a
        # full-range slice would alias the block).
        import jax.numpy as jnp
        staged: List[Tuple[Tuple, int, int, int,
                           Dict[int, Tuple[Any, int]]]] = []
        for fkey, pidx, start, plen, sel in claims:
            m: Dict[int, Tuple[Any, int]] = {}
            for lo, hi in _runs(sel):
                frag = jnp.array(block[sel[lo]:sel[hi - 1] + 1])
                _ms.track_array(
                    frag, "cache",
                    device=plan.dev if plan.device is not None else None)
                for k in range(lo, hi):
                    m[int(seg_rows[sel[k]])] = (frag, k - lo)
            staged.append((fkey, pidx, start, plen, m))
        # phase 3 (locked): install fragments (setdefault — a racing
        # loader's duplicate copies are dropped and collected) + the
        # completion check
        complete: List[Tuple[int, int, Dict[int, Tuple[Any, int]]]] = []
        with self._lock:
            for fkey, pidx, start, plen, m in staged:
                if fkey in self._pages:
                    continue
                buf = self._fill.get(fkey)
                if buf is None:
                    buf = self._fill[fkey] = {}
                    while len(self._fill) > _MAX_FILL_PAGES:
                        self._drop_fill_locked(
                            next(iter(self._fill)))
                else:
                    self._fill.move_to_end(fkey)
                for r, v in m.items():
                    buf.setdefault(r, v)
                self._refresh_fill_bytes_locked(fkey, buf)
                if len(buf) == plen:
                    self._drop_fill_locked(fkey, keep=buf)
                    complete.append((pidx, start, buf))
            evicted = self._evict_down_locked(plan.dev)
        if evicted:
            # metric/trace work outside the lock, same as every other
            # eviction site — dashboards must see fill-pressure churn
            _M_EVICTIONS.labels(device=plan.dev).inc(evicted)
            _tracing.add_event("cache.evict", device=plan.dev,
                               pages=evicted)
        for pidx, start, buf in complete:
            self._build_page(plan, pidx, start, buf, hw)

    def _refresh_fill_bytes_locked(self, fkey: Tuple,
                                   buf: Dict[int, Tuple[Any, int]]
                                   ) -> None:
        new = sum(f.nbytes for f in
                  {id(f): f for f, _ in buf.values()}.values())
        old = self._fill_nbytes.get(fkey, 0)
        self._fill_nbytes[fkey] = new
        dev = fkey[0]
        self._fill_dev[dev] = self._fill_dev.get(dev, 0) + new - old

    def _drop_fill_locked(self, fkey: Tuple, keep=None) -> None:
        """Remove one fill buffer and its byte accounting (`keep` =
        the buffer is graduating to a page build, not being
        discarded — the caller already holds it)."""
        buf = self._fill.pop(fkey, None)
        old = self._fill_nbytes.pop(fkey, 0)
        if buf is not None or keep is not None:
            dev = fkey[0]
            self._fill_dev[dev] = max(
                self._fill_dev.get(dev, 0) - old, 0)

    def _build_page(self, plan: Plan, pidx: int, start: int,
                    buf: Dict[int, Tuple[Any, int]],
                    hw: Optional[Tuple[int, int]]) -> None:
        """Concatenate a completed page's device blocks (runs of
        consecutive offsets in one block become a single slice) and
        insert it, evicting LRU unpinned pages past the capacity
        target."""
        import jax.numpy as jnp
        key = (plan.dev,) + plan.skey + (pidx,)
        try:
            if _faults.ACTIVE:
                # the chaos site for the fill path: an injected device
                # OOM here is ABSORBED (the cache degrades, the task
                # proceeds) — detail leads "cache" so plans can target
                # it apart from argument staging
                _faults.inject("memory.pressure",
                               detail=f"cache page {plan.dev} p{pidx}")
            rows = sorted(buf)
            parts = []
            i = 0
            while i < len(rows):
                frag, off = buf[rows[i]]
                j = i + 1
                while j < len(rows):
                    f2, o2 = buf[rows[j]]
                    if f2 is not frag or o2 != off + (j - i):
                        break
                    j += 1
                if off == 0 and j - i == int(frag.shape[0]):
                    parts.append(frag)  # whole fragment, reuse as-is
                else:
                    parts.append(frag[off:off + (j - i)])
                i = j
            if len(parts) == 1 and parts[0] is buf[rows[0]][0]:
                # single whole fragment: already pool-owned and
                # ledger-tracked (kind=cache) at offer time
                data = parts[0]
            else:
                data = parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts, axis=0)
                _ms.track_array(data, "cache",
                                device=plan.dev
                                if plan.device is not None else None)
        except Exception as e:  # noqa: BLE001 — caching is best-effort
            if _ms.is_oom(e):
                _ms.note_oom(e, site="cache",
                             detail=f"page build on {plan.dev}")
            _log.warning("frame-cache page build failed on %s: %s",
                         plan.dev, e)
            return
        page = _Page(key, data, start, len(rows),
                     hw or plan.hw or (0, 0))
        evicted = 0
        with self._lock:
            if key in self._pages:
                return  # racing loader built it first
            self._pages[key] = page
            self._live[plan.dev] = self._live.get(plan.dev, 0) \
                + page.nbytes
            self._inserts[plan.dev] = self._inserts.get(plan.dev, 0) + 1
            # pin into the building task's lease: a gather may follow,
            # and eviction mid-flight would free nothing
            self._pin_locked(page, plan.lease)
            evicted = self._evict_down_locked(plan.dev)
        _M_INSERTS.labels(device=plan.dev).inc()
        if evicted:
            _M_EVICTIONS.labels(device=plan.dev).inc(evicted)
            _tracing.add_event("cache.evict", device=plan.dev,
                               pages=evicted)

    def assemble(self, plan: Plan, fresh_rows: np.ndarray,
                 fresh_data: np.ndarray,
                 hw: Optional[Tuple[int, int]] = None) -> Any:
        """Build the device array for ``plan.rows``: a page-table
        gather over pinned pages plus ONE staging copy per contiguous
        run of fresh (decoded) rows — and every staged run is offered
        toward page completion on the way through, so the pool fills
        as a side effect of exactly the h2d the task pays anyway."""
        return self._assemble(plan, plan.rows, fresh_rows, fresh_data,
                              hw)

    def assemble_rows(self, plan: Plan, rows: np.ndarray,
                      fresh_rows: np.ndarray, fresh_data: np.ndarray,
                      hw: Optional[Tuple[int, int]] = None) -> Any:
        """Chunk-granular assemble (work-packet streaming): gather an
        arbitrary sorted subset of the plan's rows."""
        return self._assemble(plan, np.asarray(rows, np.int64),
                              fresh_rows, fresh_data, hw)

    def _assemble(self, plan: Plan, rows: np.ndarray,
                  fresh_rows: np.ndarray, fresh_data: np.ndarray,
                  hw: Optional[Tuple[int, int]] = None) -> Any:
        import jax.numpy as jnp
        fresh_rows = np.asarray(fresh_rows, np.int64)
        pf = plan.page_frames
        # classify each requested row: resident page (hit at plan time
        # or inserted by offer() just now — re-check under the lock,
        # pinning any newly used page) or fresh decode
        with self._lock:
            pages: Dict[int, _Page] = {}
            lease_pages = set(id(p) for p in plan.lease._pages)
            src: List[Optional[_Page]] = []
            for r in rows.tolist():
                pidx = r // pf
                page = pages.get(pidx)
                if page is None:
                    key = (plan.dev,) + plan.skey + (pidx,)
                    page = self._pages.get(key)
                    if page is not None:
                        if not 0 <= r - page.start < page.n:
                            page = None
                    if page is not None:
                        pages[pidx] = page
                        self._pages.move_to_end(key)
                        if id(page) not in lease_pages:
                            self._pin_locked(page, plan.lease)
                            lease_pages.add(id(page))
                src.append(page)
        # segments: maximal runs of rows served by the same source
        segs: List[Tuple[Optional[_Page], int, int]] = []
        for i, page in enumerate(src):
            if segs and segs[-1][0] is page:
                segs[-1] = (page, segs[-1][1], i + 1)
            else:
                segs.append((page, i, i + 1))
        # zero-copy fast path: the request is exactly one whole page
        if len(segs) == 1 and segs[0][0] is not None:
            page, lo, hi = segs[0]
            if hi - lo == page.n and int(rows[0]) == page.start \
                    and int(rows[-1]) == page.start + page.n - 1:
                return page.data
        parts = []
        for page, lo, hi in segs:
            seg_rows = rows[lo:hi]
            if page is not None:
                local = seg_rows - page.start
                if len(local) > 1 and bool((np.diff(local) == 1).all()):
                    parts.append(page.data[int(local[0]):
                                           int(local[-1]) + 1])
                else:
                    parts.append(page.data[jnp.asarray(local)])
            else:
                pos = np.searchsorted(fresh_rows, seg_rows)
                if (pos >= len(fresh_rows)).any() or \
                        (fresh_rows[pos] != seg_rows).any():
                    raise CacheBypass(
                        "assemble: rows neither resident nor freshly "
                        "decoded")
                if len(pos) > 1 and bool((np.diff(pos) == 1).all()):
                    host = fresh_data[int(pos[0]):int(pos[-1]) + 1]
                else:
                    host = fresh_data[pos]
                staged = _stage(np.ascontiguousarray(host),
                                plan.device, plan.dev, kind="staging")
                parts.append(staged)
                # page fill rides this same staged block on device —
                # never a second h2d for rows the task already shipped
                self._offer_block(plan, seg_rows, staged, hw)
        if not parts:
            return jnp.zeros((0,) + tuple(fresh_data.shape[1:]),
                             fresh_data.dtype)
        if len(parts) == 1:
            return parts[0]
        return jnp.concatenate(parts, axis=0)

    # -- pinning / eviction ---------------------------------------------

    def _pin_locked(self, page: _Page, lease: Lease) -> None:
        if lease._released:
            # the task already ended (revoked/failed mid-stream): a pin
            # added now could never be released — leave the page
            # unpinned; the dead task's gather is refcount-safe anyway
            return
        page.pins += 1
        lease._pages.append(page)
        if page.pins == 1:
            dev = page.key[0]
            self._pinned[dev] = self._pinned.get(dev, 0) + page.nbytes

    def _release_lease(self, lease: Lease) -> None:
        with self._lock:
            if lease._released:
                return
            lease._released = True
            for page in lease._pages:
                page.pins -= 1
                if page.pins == 0:
                    dev = page.key[0]
                    self._pinned[dev] = max(
                        self._pinned.get(dev, 0) - page.nbytes, 0)
            lease._pages = []

    def _evict_down_locked(self, dev: str,
                           target: Optional[int] = None) -> int:
        """Pages AND fill fragments bill against the target (fragments
        are HBM like any page); incomplete fill buffers are the
        cheapest victims and go first, oldest first."""
        target = self._capacity(dev) if target is None else target
        evicted = 0
        while self._live.get(dev, 0) + self._fill_dev.get(dev, 0) \
                > target:
            fill_victim = next((k for k in self._fill if k[0] == dev),
                               None)
            if fill_victim is not None:
                self._drop_fill_locked(fill_victim)
                continue
            victim = None
            for key, page in self._pages.items():
                if key[0] == dev and page.pins == 0:
                    victim = key
                    break
            if victim is None:
                break  # everything pinned: transient overshoot
            page = self._pages.pop(victim)
            self._live[dev] = max(self._live.get(dev, 0) - page.nbytes,
                                  0)
            self._evictions[dev] = self._evictions.get(dev, 0) + 1
            evicted += 1
        return evicted

    # -- pressure actuation (ROADMAP item 5 seed) ------------------------

    def pressure_shrink(self, dev: str) -> int:
        """A firing hbm_pressure alert on `dev` sets the capacity
        target to HALF the cache's current occupancy (bounded by the
        old target, never below MIN_CAPACITY_BYTES) and evicts down
        NOW.  Deliberately occupancy-based, not target-based: with the
        pool under-full, halving a slack 256 MB target would evict
        nothing — pressure means the device needs bytes back
        immediately.  The shrunk target persists for the process (a
        device that hit pressure once is overcommitted; operators
        resize via [perf] frame_cache_mb)."""
        with self._lock:
            # single-chip / affinity-off pools key pages under
            # "default" (TaskItem.device is None there) while the
            # hbm_pressure alert names the real chip label: redirect so
            # the actuation reaches the pages that actually exist
            if dev not in self._live and dev not in self._fill_dev \
                    and ("default" in self._live
                         or "default" in self._fill_dev):
                _log.info("pressure shrink for %s redirected to the "
                          "default-placement pool", dev)
                dev = "default"
            occupied = self._live.get(dev, 0) + self._fill_dev.get(dev,
                                                                   0)
            cur = min(self._capacity(dev), max(occupied,
                                               MIN_CAPACITY_BYTES))
            new = max(MIN_CAPACITY_BYTES, cur // 2)
            self._target[dev] = new
            self._shrinks[dev] = self._shrinks.get(dev, 0) + 1
            evicted = self._evict_down_locked(dev, new)
        self._ensure_gauges(dev)
        _M_SHRINKS.labels(device=dev).inc()
        if evicted:
            _M_EVICTIONS.labels(device=dev).inc(evicted)
            _tracing.add_event("cache.evict", device=dev, pages=evicted,
                               reason="hbm_pressure")
        _log.warning(
            "hbm_pressure on %s: frame-cache target shrunk to %d MB "
            "(%d page(s) evicted)", dev, new >> 20, evicted)
        return evicted

    # -- introspection ---------------------------------------------------

    def status_dict(self) -> Dict[str, Any]:
        """The /statusz Frame-cache panel (per device)."""
        with self._lock:
            devs = sorted(set(self._live) | set(self._hits)
                          | set(self._misses) | set(self._fill_dev))
            pages: Dict[str, int] = {}
            for key in self._pages:
                pages[key[0]] = pages.get(key[0], 0) + 1
            out = {}
            for d in devs:
                h = self._hits.get(d, 0)
                m = self._misses.get(d, 0)
                out[d] = {
                    "pages": pages.get(d, 0),
                    "live_bytes": self._live.get(d, 0),
                    "fill_bytes": self._fill_dev.get(d, 0),
                    "pinned_bytes": self._pinned.get(d, 0),
                    "capacity_bytes": self._capacity(d),
                    "hits": h, "misses": m,
                    "hit_rate": round(h / (h + m), 4) if h + m else None,
                    "evictions": self._evictions.get(d, 0),
                    "pressure_shrinks": self._shrinks.get(d, 0),
                }
        return {"enabled": _ENABLED, "devices": out,
                "page_frames": {"/".join(map(str, k)): v
                                for k, v in self._page_frames.items()}}

    def clear(self) -> None:
        """Drop every unpinned page and all fill buffers (tests, bench
        A/B resets; table-rewrite hygiene is keyed by table id, which
        create_table mints fresh).  PINNED pages survive — an in-flight
        streaming task's plan-time hits must stay resident (its
        assemble has no fallback for rows that vanish mid-task), same
        rule eviction follows."""
        with self._lock:
            for key in [k for k, p in self._pages.items()
                        if p.pins == 0]:
                del self._pages[key]
            self._fill.clear()
            self._fill_nbytes.clear()
            self._fill_dev = {d: 0 for d in self._fill_dev}
            self._page_frames.clear()
            live: Dict[str, int] = {d: 0 for d in self._live}
            for key, p in self._pages.items():
                live[key[0]] = live.get(key[0], 0) + p.nbytes
            self._live = live
            self._target.clear()


def _stage(host: np.ndarray, device: Any, dev: str, kind: str) -> Any:
    """The cache's fresh-row staging: the shared batch.staged_device_put
    contract (fault site, OOM forensics at site=staging, h2d meters,
    ledger) with a detail that LEADS with the ledger kind, so chaos
    plans can target argument staging (match=staging, propagates) apart
    from the absorbed page-build site (match=cache — _build_page arms
    its own injection)."""
    from .batch import staged_device_put
    return staged_device_put(
        host, device, kind,
        fault_detail=f"{kind} h2d {dev} {host.nbytes}b")


# ---------------------------------------------------------------------------
# process-wide singleton + hbm_pressure wiring
# ---------------------------------------------------------------------------

_CACHE: Optional[FrameCache] = None
_CACHE_LOCK = threading.Lock()


def _on_alert(transition: Dict[str, Any]) -> None:
    """The shrink actuation: hbm_pressure firing -> shrink + evict.
    Registered with the remediation controller as the
    ``shrink_frame_cache`` action behind the ``frame_cache_shrink``
    playbook (engine/controller.py — this was the PR 10 hard-wired
    health listener, generalized: cooldown, dry-run, audit and the
    SCANNER_TPU_REMEDIATION kill switch now apply).  Still callable
    directly with a transition dict — the rule/state filter stays so
    private health engines can use it as a bare listener in tests."""
    if transition.get("rule") != "hbm_pressure" \
            or transition.get("state") != "firing":
        return
    dev = (transition.get("labels") or {}).get("device")
    if dev and _CACHE is not None:
        try:
            _CACHE.pressure_shrink(dev)
        except Exception:  # noqa: BLE001 — actuation must never kill
            _log.exception("pressure shrink failed for %s", dev)


def cache() -> FrameCache:
    """The process-wide pool (created on first use; binds the
    hbm_pressure shrink to the remediation controller's
    frame_cache_shrink playbook).  With SCANNER_TPU_REMEDIATION=0 the
    controller never attaches to the health engine, so the cache is
    signal-only: the alert fires, nothing shrinks."""
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is None:
            _CACHE = FrameCache()
            from . import controller as _controller
            _controller.register_action("shrink_frame_cache", _on_alert)
            _controller.ensure_started()
        return _CACHE


def status_dict() -> Dict[str, Any]:
    """Quiet form for /statusz when no cache exists yet (a scrape must
    not allocate one as a side effect)."""
    if _CACHE is None:
        return {"enabled": _ENABLED, "devices": {}, "page_frames": {}}
    return _CACHE.status_dict()


def attach_lease(task_item: Any, lease: Lease) -> None:
    """Hang a lease off its TaskItem: the executor releases it when
    evaluation finishes; the finalizer is the backstop for tasks an
    aborted pipeline never evaluates (pins must not outlive the task).
    The finalizer is installed FIRST and the list handled through a
    local — a concurrent _release_cache swap-to-None (revoked streaming
    task) must neither crash this thread nor orphan the lease."""
    weakref.finalize(task_item, lease.release)
    leases = getattr(task_item, "cache_leases", None)
    if leases is None:
        leases = []
        task_item.cache_leases = leases
    leases.append(lease)
