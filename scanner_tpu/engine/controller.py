"""Remediation controller: alerts -> actions, closed-loop.

PRs 6-10 built a complete signal plane — tracing, HBM accounting, the
health/SLO alert engine, roofline attribution — and exactly one
hard-wired actuator (the ``hbm_pressure`` -> frame-cache-shrink hook).
Everything else still pages a human: ``device_saturation`` is
documented as "the autoscaling up-signal", ``backpressure`` as the
shed signal, SIGTERM drain is chaos-verified, and nobody acts on any
of it.  This module is the actuator layer (ROADMAP item 5):

  * **Playbooks** are declarative: each maps one alert rule's
    firing/resolved transitions to a named **action**, with a
    per-playbook cooldown, a resolve-side hysteresis hold, a rate
    limit, and a dry-run mode.  The built-in set (``DEFAULT_PLAYBOOKS``)
    covers the four families serving millions of users on preemptible
    TPUs needs handled without a pager:

      - ``autoscale_up``        device_saturation -> nudge the autoscaler
      - ``admission_pause``     stage_backpressure -> shed load (pause
                                job admission; resume on resolve after
                                hysteresis) instead of melting
      - ``ladder_rewarm``       recompile_storm -> re-warm the bucket
                                ladders (engine/evaluate.py)
      - ``frame_cache_shrink``  hbm_pressure -> shrink + evict the paged
                                frame cache (the PR 10 hook, generalized)

  * **Actions are late-bound**: playbooks name actions; the component
    that owns the capability registers the callable
    (``register_action``) — the master registers admission pause/resume
    and the autoscaler, the frame cache registers its shrink, this
    module registers the ladder re-warm.  A playbook whose action is
    unbound in this process records outcome ``unbound`` and does
    nothing (a worker has no admission to pause).

  * **Every decision is audited**: a bounded in-process audit ring
    (``audit()``, surfaced on /statusz) and
    ``scanner_tpu_remediations_total{playbook,action,outcome}``
    (outcomes: applied | dry_run | cooldown | rate_limited | unbound |
    error) — a remediation that fired, was vetoed, or broke is always
    attributable after the fact.

  * The **autoscaler** (``Autoscaler``) is the master-side loop: it
    folds device saturation, master queue depth and worker liveness
    into a desired replica count within ``[min, max]`` bounds and
    invokes a pluggable actuator — ``deploy.Cluster.scale`` in
    production (kubernetes drains pods via SIGTERM ->
    ``Worker.drain``), a callback in tests.  Scale-down happens only
    when the cluster is idle and only via drain: in-flight tasks are
    never killed.

``SCANNER_TPU_REMEDIATION=0`` (or ``[remediation] enabled = false``)
is the kill switch: the controller never binds to the health engine
and the system returns to signal-only behavior — alerts fire, humans
act.  ``[remediation] dry_run`` keeps the whole decision pipeline live
but stops short of invoking actions (the staging-environment mode).
See docs/robustness.md §Remediation playbooks for the matrix
(scanner-check SC311 keeps it honest).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..util import health as _health
from ..util import metrics as _mx
from ..util.log import get_logger
from ..util.tracing import _env_on

_log = get_logger("controller")

# the [remediation] config section contract — config.default_config()
# must declare exactly these keys (scanner-check SC311 enforces both
# directions, like [alerts]/CONFIG_KEYS under SC308)
CONFIG_KEYS = ("enabled", "dry_run", "autoscale_min", "autoscale_max")

# action outcomes the metric/audit vocabulary admits
OUTCOMES = ("applied", "dry_run", "cooldown", "rate_limited", "unbound",
            "error")

AUDIT_RING = 256

_M_REMEDIATIONS = _mx.registry().counter(
    "scanner_tpu_remediations_total",
    "Remediation-playbook decisions by playbook, action and outcome "
    "(applied | dry_run | cooldown | rate_limited | unbound | error) — "
    "the audit counter of the alerts->actuation loop "
    "(engine/controller.py).",
    labels=["playbook", "action", "outcome"])
_M_DESIRED = _mx.registry().gauge(
    "scanner_tpu_autoscale_desired_replicas",
    "Worker replica count the autoscaler currently wants (within its "
    "[min,max] bounds); compare against "
    "scanner_tpu_master_workers_active to see convergence.")


# the shared kill-switch truthiness helper (util/tracing.py — the same
# one framecache/coststats use), not a fourth copy of the rules
_ENABLED = _env_on("SCANNER_TPU_REMEDIATION")
_DRY_RUN = False


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """The programmatic override ([remediation] enabled config key);
    the SCANNER_TPU_REMEDIATION env var is read at import and wins
    when set.  Disabling after start is honored at transition time —
    the controller checks the flag on every delivery."""
    global _ENABLED
    _ENABLED = bool(on)


def set_dry_run(on: bool) -> None:
    """[remediation] dry_run: decisions run end to end (cooldown,
    hysteresis, rate limit, audit, metrics) but no action is invoked."""
    global _DRY_RUN
    _DRY_RUN = bool(on)


def dry_run() -> bool:
    return _DRY_RUN


# ---------------------------------------------------------------------------
# Playbooks
# ---------------------------------------------------------------------------

@dataclass
class Playbook:
    """One alert->action binding.

    `action` runs on the alert's `firing` transition; `resolve_action`
    (optional) runs once the alert has stayed resolved for
    `hysteresis_s` (checked on tick(); a re-fire cancels the pending
    resolve) — the flap damper for reversible actions like admission
    pause/resume.  `cooldown_s` is per (playbook, alert-label-group):
    hbm_pressure on chip A must not block remediation of chip B.
    `max_per_window` actions per `window_s` is the global runaway
    brake per playbook."""

    name: str
    alert: str                       # a health DEFAULT_RULES name (SC311)
    action: str
    resolve_action: str = ""
    cooldown_s: float = 30.0
    hysteresis_s: float = 0.0
    max_per_window: int = 6
    window_s: float = 600.0
    description: str = ""


# The built-in playbook set every process evaluates when remediation is
# on.  Names and alert bindings are a contract: the docs/robustness.md
# remediation-playbooks marker table and this tuple may not drift, and
# every `alert` must name a health DEFAULT_RULES rule (scanner-check
# SC311, all pairings both directions).
DEFAULT_PLAYBOOKS = (
    Playbook(
        name="autoscale_up", alert="device_saturation",
        action="autoscale", cooldown_s=15.0, max_per_window=12,
        description="sustained chip saturation nudges the autoscaler "
                    "to re-evaluate its desired replica count now "
                    "(the periodic master observe loop is the "
                    "fallback); scale-up within [min,max] bounds"),
    Playbook(
        name="admission_pause", alert="stage_backpressure",
        action="pause_admission", resolve_action="resume_admission",
        cooldown_s=5.0, hysteresis_s=2.0, max_per_window=12,
        description="sustained backpressure pauses new-job admission "
                    "on the master (NewJob answers retryable "
                    "admission_paused) instead of letting queues melt; "
                    "admission resumes once the alert has stayed "
                    "resolved for the hysteresis hold"),
    Playbook(
        name="ladder_rewarm", alert="recompile_storm",
        action="rewarm_ladders", cooldown_s=60.0, max_per_window=6,
        description="a sustained XLA recompile rate re-warms every "
                    "live evaluator's bucket ladder on a background "
                    "thread (engine/evaluate.py rewarm_all) so steady "
                    "state returns to zero compiles per task"),
    Playbook(
        name="frame_cache_shrink", alert="hbm_pressure",
        action="shrink_frame_cache", cooldown_s=5.0, max_per_window=12,
        description="HBM occupancy near the device limit shrinks the "
                    "paged frame cache's capacity target and evicts "
                    "down NOW, before OOM strikes a task (the PR 10 "
                    "hard-wired hook as a registered playbook)"),
)


def default_playbooks() -> List[Playbook]:
    return list(DEFAULT_PLAYBOOKS)


# ---------------------------------------------------------------------------
# Controller
# ---------------------------------------------------------------------------

def _labels_key(labels: Optional[Dict[str, Any]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class RemediationController:
    """Delivers alert transitions to playbooks and invokes their bound
    actions under cooldown/hysteresis/rate-limit/dry-run discipline.

    One per process via `controller()`, bound to the health engine by
    `ensure_started()`; tests build private ones with a synthetic
    clock and drive `on_transition`/`tick` by hand.  Actions run
    OUTSIDE the controller lock (they may take seconds — a kubectl
    scale, a cache eviction sweep) and their exceptions are absorbed
    into outcome=error: a broken actuator must never kill alert
    delivery."""

    def __init__(self, playbooks: Optional[List[Playbook]] = None,
                 clock: Callable[[], float] = time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._playbooks: Dict[str, Playbook] = {}
        self._actions: Dict[str, Callable[[dict], Any]] = {}
        # (playbook, labels-key) -> last applied-action time (cooldown)
        self._last_action: Dict[Tuple[str, Tuple], float] = {}
        # playbook -> deque of applied-action times (rate limit window)
        self._recent: Dict[str, Deque[float]] = {}
        # playbook -> label-groups currently firing: alerts fire per
        # (rule, label-group), so "resolved" only means resolved once
        # EVERY group has resolved — one stage recovering must not
        # resume admission while another is still backpressured
        self._firing_groups: Dict[str, set] = {}
        # playbook -> resolved-at time awaiting the hysteresis hold
        self._pending_resolve: Dict[str, Tuple[float, dict]] = {}
        self._audit: Deque[dict] = deque(maxlen=AUDIT_RING)
        for pb in (default_playbooks() if playbooks is None
                   else playbooks):
            self._playbooks[pb.name] = pb

    # -- registration -------------------------------------------------------

    def register(self, playbook: Playbook) -> None:
        with self._lock:
            self._playbooks[playbook.name] = playbook

    def unregister(self, name: str) -> None:
        with self._lock:
            self._playbooks.pop(name, None)
            self._pending_resolve.pop(name, None)
            self._firing_groups.pop(name, None)

    def playbooks(self) -> List[Playbook]:
        with self._lock:
            return list(self._playbooks.values())

    def register_action(self, name: str,
                        fn: Callable[[dict], Any]) -> None:
        """Bind the callable behind an action name.  `fn` receives the
        triggering transition dict ({"state","rule","labels","value"});
        its return value is recorded in the audit entry's detail."""
        with self._lock:
            self._actions[name] = fn

    def unregister_action(self, name: str,
                          owner: Optional[Callable] = None) -> None:
        """Remove an action binding.  With `owner` given, remove only
        if the CURRENT binding is that callable — a stopped component
        must not strip a newer same-process sibling's re-registration
        (two Masters in one test process: latest wins, the old one's
        stop() may run later)."""
        with self._lock:
            if owner is not None and self._actions.get(name) != owner:
                return
            self._actions.pop(name, None)

    # -- bookkeeping shared with the autoscaler -----------------------------

    def record(self, playbook: str, action: str, outcome: str,
               detail: Any = None,
               labels: Optional[Dict[str, Any]] = None) -> None:
        """One audited remediation decision (the metric + audit-ring
        write every path funnels through, including the autoscaler's)."""
        _M_REMEDIATIONS.labels(playbook=playbook, action=action,
                               outcome=outcome).inc()
        entry = {"t": self._clock(), "playbook": playbook,
                 "action": action, "outcome": outcome,
                 "labels": dict(labels or {}),
                 "detail": detail}
        with self._lock:
            self._audit.append(entry)
        log = _log.warning if outcome in ("applied", "error") \
            else _log.info
        log("remediation %s/%s -> %s%s", playbook, action, outcome,
            f" ({detail})" if detail not in (None, "") else "")

    def audit(self, n: int = AUDIT_RING) -> List[dict]:
        with self._lock:
            return list(self._audit)[-n:]

    def status_dict(self) -> Dict[str, Any]:
        """The /statusz Remediation panel: enabled/dry-run flags, the
        playbook table, and the newest audit entries."""
        with self._lock:
            pbs = [{"name": p.name, "alert": p.alert,
                    "action": p.action,
                    "resolve_action": p.resolve_action,
                    "cooldown_s": p.cooldown_s,
                    "hysteresis_s": p.hysteresis_s,
                    "bound": p.action in self._actions}
                   for p in self._playbooks.values()]
            audit = list(self._audit)[-16:]
        return {"enabled": _ENABLED, "dry_run": _DRY_RUN,
                "playbooks": pbs, "audit": audit}

    # -- the action gate ----------------------------------------------------

    def _invoke(self, pb: Playbook, action: str, transition: dict,
                gate_cooldown: bool) -> str:
        now = self._clock()
        lkey = (pb.name, _labels_key(transition.get("labels")))
        with self._lock:
            fn = self._actions.get(action)
            if fn is None:
                outcome = "unbound"
            elif gate_cooldown and now - self._last_action.get(
                    lkey, -math.inf) < pb.cooldown_s:
                outcome = "cooldown"
            else:
                recent = self._recent.setdefault(pb.name, deque())
                while recent and recent[0] <= now - pb.window_s:
                    recent.popleft()
                if gate_cooldown and len(recent) >= pb.max_per_window:
                    outcome = "rate_limited"
                else:
                    # dry-run still records cooldown/rate-limit state:
                    # the staging mode must produce the same DECISION
                    # sequence production would (applied, cooldown,
                    # rate_limited, ...), only with the invocation
                    # swapped for an audit entry
                    outcome = "dry_run" if _DRY_RUN else "applied"
                    self._last_action[lkey] = now
                    recent.append(now)
        detail = None
        if outcome == "applied":
            try:
                detail = fn(transition)
            except Exception as e:  # noqa: BLE001 — a broken actuator
                # must not kill alert delivery
                outcome = "error"
                detail = f"{type(e).__name__}: {e}"
                _log.exception("remediation action %s failed", action)
        self.record(pb.name, action, outcome, detail=detail,
                    labels=transition.get("labels"))
        return outcome

    # -- delivery -----------------------------------------------------------

    def on_transition(self, transition: dict) -> None:
        """The health-engine listener (HealthEngine.add_listener): one
        alert state transition in.  Firing -> run the playbook's action
        (cooldown/rate-limit gated); resolved -> arm the hysteresis
        hold, executed by tick()."""
        if not _ENABLED:
            return
        rule = transition.get("rule")
        state = transition.get("state")
        lkey = _labels_key(transition.get("labels"))
        with self._lock:
            matched = [p for p in self._playbooks.values()
                       if p.alert == rule]
        for pb in matched:
            if state == "firing":
                with self._lock:
                    self._firing_groups.setdefault(pb.name,
                                                   set()).add(lkey)
                    self._pending_resolve.pop(pb.name, None)
                self._invoke(pb, pb.action, transition,
                             gate_cooldown=True)
            elif state == "resolved":
                with self._lock:
                    groups = self._firing_groups.get(pb.name)
                    if groups is not None:
                        groups.discard(lkey)
                    # one label-group resolving is not the alert
                    # resolving: the reversal waits until EVERY group
                    # is clear (stage=save recovering must not resume
                    # admission while stage=load still backpressures)
                    still_firing = bool(groups)
                if not pb.resolve_action or still_firing:
                    continue
                if pb.hysteresis_s <= 0:
                    self._invoke(pb, pb.resolve_action, transition,
                                 gate_cooldown=False)
                else:
                    with self._lock:
                        self._pending_resolve[pb.name] = (
                            self._clock(), dict(transition))

    def tick(self, now: Optional[float] = None) -> None:
        """Run pending resolve actions whose hysteresis hold elapsed.
        Driven by the master's scan loop (and tests); processes with
        fire-only playbooks never need it."""
        if not _ENABLED:
            return
        now = now if now is not None else self._clock()
        due: List[Tuple[Playbook, dict]] = []
        with self._lock:
            for name, (t0, transition) in list(
                    self._pending_resolve.items()):
                pb = self._playbooks.get(name)
                if pb is None:
                    del self._pending_resolve[name]
                    continue
                if now - t0 >= pb.hysteresis_s:
                    del self._pending_resolve[name]
                    due.append((pb, transition))
        for pb, transition in due:
            self._invoke(pb, pb.resolve_action, transition,
                         gate_cooldown=False)


# ---------------------------------------------------------------------------
# Autoscaler
# ---------------------------------------------------------------------------

@dataclass
class AutoscaleConfig:
    """Bounds + pacing for the master-side replica loop.  The desired
    count derives from backlog (queued+outstanding tasks over
    `queue_per_worker`) and saturation; scale-down requires the
    cluster idle for `idle_grace_s` and steps one replica at a time —
    preemptible capacity comes back cheap, killed work does not."""

    min_replicas: int = 1
    max_replicas: int = 8
    # one worker per this many backlog tasks (the queue-depth signal)
    queue_per_worker: float = 4.0
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0
    # the cluster must be fully idle this long before a scale-down
    idle_grace_s: float = 60.0


class Autoscaler:
    """Folds saturation + queue depth + liveness into a desired replica
    count and invokes the actuator through the controller's audited
    action gate.  The actuator contract is `scale(n)` where the
    deployment layer reduces capacity only by draining
    (deploy.Cluster.scale -> kubernetes SIGTERM -> Worker.drain):
    this loop never kills in-flight work, and additionally refuses to
    scale down while any task is queued or outstanding."""

    def __init__(self, config: AutoscaleConfig,
                 actuator: Optional[Callable[[int], Any]] = None,
                 controller: Optional[RemediationController] = None,
                 clock: Callable[[], float] = time.time):
        self.config = config
        self._actuator = actuator
        self._controller = controller
        self._clock = clock
        self._lock = threading.Lock()
        self._desired: Optional[int] = None
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._idle_since: Optional[float] = None

    def desired(self) -> Optional[int]:
        with self._lock:
            return self._desired

    def _clamp(self, n: int) -> int:
        return max(self.config.min_replicas,
                   min(self.config.max_replicas, n))

    def _apply(self, target: int, direction: str, detail: str) -> bool:
        """Invoke the actuator; False means the desired count must roll
        back (a failed kubectl/API call would otherwise latch _desired
        at the new target and every later observation would see
        nothing left to do while the cluster stays under-provisioned)."""
        ctrl = self._controller or controller()
        playbook = f"autoscale_{direction}"
        if self._actuator is None:
            ctrl.record(playbook, "scale", "unbound", detail=detail)
            return True
        if _DRY_RUN:
            ctrl.record(playbook, "scale", "dry_run", detail=detail)
            return True
        try:
            self._actuator(target)
        except Exception as e:  # noqa: BLE001 — audited, never fatal
            ctrl.record(playbook, "scale", "error",
                        detail=f"{type(e).__name__}: {e}")
            _log.exception("autoscale actuator failed (target=%d)",
                           target)
            return False
        ctrl.record(playbook, "scale", "applied", detail=detail)
        return True

    def observe(self, *, workers: int, queued: int, outstanding: int,
                saturated_workers: int = 0,
                now: Optional[float] = None) -> Optional[int]:
        """One observation of the cluster -> possibly one scale action.
        Returns the new desired count when a scale was decided (even in
        dry-run), else None.  Called from the master's scan loop and by
        the `autoscale` playbook on a device_saturation firing."""
        if not _ENABLED:
            return None
        now = now if now is not None else self._clock()
        cfg = self.config
        acted: Optional[int] = None
        prev_desired: Optional[int] = None
        with self._lock:
            if self._desired is None:
                self._desired = self._clamp(max(workers,
                                                cfg.min_replicas))
            backlog = int(queued) + int(outstanding)
            need = math.ceil(backlog / cfg.queue_per_worker) \
                if backlog else 0
            target = need
            if saturated_workers > 0 and queued > 0:
                # chips saturated AND work waiting: one more replica
                # even if the backlog math alone is satisfied
                target = max(target, self._desired + 1)
            target = self._clamp(target) if target else cfg.min_replicas
            up = target > self._desired
            idle = backlog == 0 and saturated_workers == 0
            if not idle:
                self._idle_since = None
            elif self._idle_since is None:
                self._idle_since = now
            if up and now - self._last_up >= cfg.up_cooldown_s:
                prev_desired = self._desired
                self._desired = target
                self._last_up = now
                self._idle_since = None
                acted = target
                direction, why = "up", (
                    f"backlog={backlog} saturated={saturated_workers} "
                    f"workers={workers} -> {target}")
            elif (idle and self._desired > cfg.min_replicas
                    and self._idle_since is not None
                    and now - self._idle_since >= cfg.idle_grace_s
                    and now - self._last_down >= cfg.down_cooldown_s):
                # idle long enough: step down ONE replica via drain
                prev_desired = self._desired
                self._desired -= 1
                self._last_down = now
                self._idle_since = now
                acted = self._desired
                direction, why = "down", (
                    f"idle >= {cfg.idle_grace_s:.0f}s "
                    f"-> {self._desired} (drain)")
            desired = self._desired
        _M_DESIRED.set(desired)
        if acted is not None:
            if not self._apply(acted, direction, why):
                # failed actuation: roll back so later observations
                # keep retrying toward the target (the cooldown just
                # consumed paces the retries — a broken actuator is
                # not hammered every scan pass)
                with self._lock:
                    self._desired = prev_desired
                _M_DESIRED.set(prev_desired)
                return None
        return acted


# ---------------------------------------------------------------------------
# Process-wide singleton (mirrors health.engine())
# ---------------------------------------------------------------------------

_CONTROLLER: Optional[RemediationController] = None
_CONTROLLER_LOCK = threading.Lock()
# [remediation] autoscale bounds as deployment defaults; Master builds
# its AutoscaleConfig from these when given autoscale=True
_AUTOSCALE_BOUNDS = (1, 8)


def controller() -> RemediationController:
    global _CONTROLLER
    with _CONTROLLER_LOCK:
        if _CONTROLLER is None:
            _CONTROLLER = RemediationController()
        return _CONTROLLER


def ensure_started() -> Optional[RemediationController]:
    """Bind the process controller to the health engine (idempotent);
    no-op when SCANNER_TPU_REMEDIATION=0 / [remediation]
    enabled=false — alerts stay signal-only.  Also registers the
    actions this module owns itself (the bucket-ladder re-warm)."""
    if not _ENABLED:
        return None
    c = controller()
    c.register_action("rewarm_ladders", _rewarm_ladders)
    _health.add_listener(c.on_transition)
    return c


def register_action(name: str, fn: Callable[[dict], Any]) -> None:
    controller().register_action(name, fn)


def unregister_action(name: str,
                      owner: Optional[Callable] = None) -> None:
    controller().unregister_action(name, owner=owner)


def set_autoscale_bounds(min_replicas: int, max_replicas: int) -> None:
    """[remediation] autoscale_min/max config wiring (deployment
    defaults read by Master(autoscale=True))."""
    global _AUTOSCALE_BOUNDS
    _AUTOSCALE_BOUNDS = (max(0, int(min_replicas)),
                         max(1, int(max_replicas)))


def autoscale_bounds() -> Tuple[int, int]:
    return _AUTOSCALE_BOUNDS


def status_dict() -> Dict[str, Any]:
    """Process remediation status; quiet when the controller was never
    created (a scrape must not spin one up as a side effect)."""
    if _CONTROLLER is None:
        return {"enabled": _ENABLED, "dry_run": _DRY_RUN,
                "playbooks": [], "audit": []}
    return _CONTROLLER.status_dict()


def _rewarm_ladders(transition: dict) -> str:
    """The recompile_storm playbook's action: re-schedule the bucket
    ladder warm-up on every live evaluator (best-effort; with the
    persistent compilation cache configured the re-warm is mostly
    cache hits re-pinning executables)."""
    from . import evaluate as _evaluate
    n = _evaluate.rewarm_all()
    return f"rewarmed {n} kernel ladder(s)"
