from .client import Client, Table
from .executor import LocalExecutor

__all__ = ["Client", "Table", "LocalExecutor"]
