"""Per-task graph evaluation.

Capability parity: reference scanner/engine/evaluate_worker.cpp:408-1328
(EvaluateWorker: row bookkeeping, stencil cache, batching, builtin
sample/space/slice/unslice remapping, per-slice arg rebinding, state reset).

One TaskEvaluator owns the kernel instances of one pipeline instance and
executes tasks end-to-end in element space: {(node_id, column): {row: elem}}.
Frames are numpy uint8 arrays; TPU kernels receive whole batches and jit
internally.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import (DeviceType, GraphException, JobException, NullElement,
                      ScannerException, SliceList)
from ..graph import analysis as A
from ..graph import ops as O
from ..util.profiler import Profiler

Elem = Any  # np.ndarray | bytes | arbitrary python object | NullElement
ColKey = Tuple[int, str]  # (node id, column name)


def _is_null(e: Elem) -> bool:
    return isinstance(e, NullElement)


class KernelInstance:
    """One live kernel with its stream/state bookkeeping."""

    def __init__(self, node: O.OpNode, profiler: Profiler,
                 devices: Optional[List[Any]] = None):
        assert node.spec is not None and node.spec.kernel_factory is not None
        self.node = node
        self.spec = node.spec
        cfg = O.KernelConfig(device=node.effective_device(),
                             args=dict(node.init_args),
                             devices=devices or [])
        self.kernel = self.spec.kernel_factory(cfg, **node.init_args)
        self.profiler = profiler
        self._cur_stream: Tuple[int, int] = (-1, -1)  # (job, slice group)
        self._last_row: Optional[int] = None
        self._did_setup = False

    def setup(self, fetch: bool = True) -> None:
        if not self._did_setup:
            if fetch:
                self.kernel.fetch_resources()
            self.kernel.setup_with_resources()
            self._did_setup = True

    def bind_stream(self, job_idx: int, slice_group: int) -> None:
        """Call new_stream when the (job, slice group) changes
        (reference evaluate_worker.cpp:640-707 per-slice arg rebinding)."""
        key = (job_idx, slice_group)
        if key == self._cur_stream:
            return
        args = {}
        for name, per_stream in self.node.job_args.items():
            if name not in self.spec.stream_arg_names:
                continue
            v = per_stream[job_idx]
            if isinstance(v, SliceList):
                v = v[slice_group]
            args[name] = v
        self.kernel.new_stream(**args)
        self.kernel.reset()
        self._cur_stream = key
        self._last_row = None

    def maybe_reset(self, row: int) -> None:
        """Reset state at row discontinuities (the reference kernel checks
        element indices itself, test_ops.cpp:183-189; we centralize it)."""
        if self._last_row is not None and row != self._last_row + 1 \
                and self.spec.is_stateful:
            self.kernel.reset()
        self._last_row = row

    def close(self) -> None:
        self.kernel.close()


class TaskEvaluator:
    def __init__(self, info: A.GraphInfo, profiler: Profiler,
                 devices: Optional[List[Any]] = None,
                 skip_fetch_resources: bool = False):
        self.info = info
        self.profiler = profiler
        self.kernels: Dict[int, KernelInstance] = {}
        for n in info.ops:
            if not n.is_builtin:
                ki = KernelInstance(n, profiler, devices)
                self.kernels[n.id] = ki
        for ki in self.kernels.values():
            ki.setup(fetch=not skip_fetch_resources)

    def close(self) -> None:
        for ki in self.kernels.values():
            ki.close()

    # ------------------------------------------------------------------

    def execute_task(self, jr: A.JobRows, plan: A.TaskPlan,
                     source_elements: Dict[int, Dict[int, Elem]]
                     ) -> Dict[int, Dict[int, Elem]]:
        """Run one task.  source_elements: Input node id -> {row: elem}.
        Returns sink node id -> {output row: elem}."""
        store: Dict[ColKey, Dict[int, Elem]] = {}
        results: Dict[int, Dict[int, Elem]] = {}

        for n in self.info.ops:
            ts = plan.streams[n.id]
            if n.name == O.INPUT_OP:
                elems = source_elements[n.id]
                store[(n.id, "output")] = elems
            elif n.name in (O.SAMPLE_OP, O.SPACE_OP):
                store[(n.id, "output")] = self._run_sampler(n, jr, plan, store)
            elif n.name == O.SLICE_OP:
                store[(n.id, "output")] = self._run_slice(n, jr, plan, store)
            elif n.name == O.UNSLICE_OP:
                store[(n.id, "output")] = self._run_unslice(n, jr, plan, store)
            elif n.name == O.OUTPUT_OP:
                src = n.input_columns()[0]
                elems = store[(src.op.id, src.column)]
                results[n.id] = {r: elems[r]
                                 for r in ts.valid_output_rows.tolist()}
            else:
                outs = self._run_kernel(n, jr, plan, store)
                for col, elems in outs.items():
                    store[(n.id, col)] = elems
        return results

    # -- builtins ------------------------------------------------------

    def _input_elems(self, n: O.OpNode, store) -> Dict[int, Elem]:
        src = n.input_columns()[0]
        return store[(src.op.id, src.column)]

    def _run_sampler(self, n, jr, plan, store) -> Dict[int, Elem]:
        ts = plan.streams[n.id]
        g = plan.slice_group if self.info.slice_level[n.id] > 0 else 0
        sampler = jr.samplers[n.id][g]
        in_elems = self._input_elems(n, store)
        up_rows = ts.valid_input_rows
        down_rows, mapping = sampler.downstream_map(up_rows)
        needed = set(ts.valid_output_rows.tolist())
        out: Dict[int, Elem] = {}
        for d, m in zip(down_rows.tolist(), mapping.tolist()):
            if d in needed:
                out[d] = NullElement() if m < 0 else in_elems[int(up_rows[m])]
        missing = needed - out.keys()
        if missing:
            raise JobException(
                f"{n.name}: missing output rows {sorted(missing)[:5]}...")
        return out

    def _run_slice(self, n, jr, plan, store) -> Dict[int, Elem]:
        ts = plan.streams[n.id]
        group = jr.partitioners[n.id].group_at(plan.slice_group)
        in_elems = self._input_elems(n, store)
        return {int(r): in_elems[int(group[r])]
                for r in ts.valid_output_rows.tolist()}

    def _run_unslice(self, n, jr, plan, store) -> Dict[int, Elem]:
        ts = plan.streams[n.id]
        inp = n.input_columns()[0].op
        offset = int(np.concatenate(
            [[0], np.cumsum(jr.rows[inp.id])])[plan.slice_group])
        in_elems = self._input_elems(n, store)
        return {int(r): in_elems[int(r) - offset]
                for r in ts.valid_output_rows.tolist()}

    # -- regular kernels -----------------------------------------------

    def _run_kernel(self, n: O.OpNode, jr: A.JobRows, plan: A.TaskPlan,
                    store) -> Dict[str, Dict[int, Elem]]:
        ts = plan.streams[n.id]
        ki = self.kernels[n.id]
        ki.bind_stream(plan.job_idx, plan.slice_group)

        in_cols = n.input_columns()
        in_maps = [store[(c.op.id, c.column)] for c in in_cols]
        g = plan.slice_group if self.info.slice_level[n.id] > 0 else 0
        in_op = in_cols[0].op
        max_in = jr.rows[in_op.id][g]
        stencil = n.effective_stencil()
        has_stencil = stencil != [0]
        batch = max(1, n.effective_batch())

        compute = ts.compute_rows.tolist()
        out_cols = [c for c, _ in n.spec.output_columns]
        outputs: Dict[str, Dict[int, Elem]] = {c: {} for c in out_cols}
        valid_out = set(ts.valid_output_rows.tolist())

        def put(row: int, result: Any) -> None:
            if row not in valid_out:
                return  # warmup row output discarded
            if len(out_cols) == 1:
                outputs[out_cols[0]][row] = result
            else:
                if not isinstance(result, tuple) or len(result) != len(out_cols):
                    raise JobException(
                        f"{n.name}: expected {len(out_cols)}-tuple output")
                for c, v in zip(out_cols, result):
                    outputs[c][row] = v

        def gather(row: int, col_map: Dict[int, Elem]):
            """Stencil window (REPEAT_EDGE clamp) or single element."""
            if has_stencil:
                window = []
                for s_off in stencil:
                    rr = min(max(row + s_off, 0), max_in - 1)
                    window.append(col_map[rr])
                return window
            return col_map[row]

        # split compute rows into contiguous runs; reset state between runs
        runs: List[List[int]] = []
        for r in compute:
            if runs and r == runs[-1][-1] + 1:
                runs[-1].append(r)
            else:
                runs.append([r])

        with self.profiler.span("evaluate:" + n.name, rows=len(compute)):
            for run in runs:
                ki.maybe_reset(run[0])
                ki._last_row = run[-1]
                for i in range(0, len(run), batch):
                    chunk = run[i:i + batch]
                    # null propagation: a row whose inputs (or stencil
                    # window) contain a null yields null without running
                    # the kernel
                    live_rows = []
                    for r in chunk:
                        window_rows = [min(max(r + s, 0), max_in - 1)
                                       for s in stencil]
                        if any(_is_null(m[wr]) for m in in_maps
                               for wr in window_rows):
                            put(r, NullElement())
                        else:
                            live_rows.append(r)
                    if not live_rows:
                        continue
                    args_per_col = []
                    for m in in_maps:
                        col_vals = [gather(r, m) for r in live_rows]
                        args_per_col.append(col_vals)
                    if batch > 1:
                        call_args = [self._maybe_stack(c)
                                     for c in args_per_col]
                        res = ki.kernel.execute(*call_args)
                        if res is None or len(res) != len(live_rows):
                            raise JobException(
                                f"{n.name}: batch kernel returned "
                                f"{0 if res is None else len(res)} results "
                                f"for {len(live_rows)} inputs")
                        for r, v in zip(live_rows, res):
                            put(r, v)
                    else:
                        for r, cols_v in zip(
                                live_rows,
                                zip(*args_per_col) if args_per_col
                                else [()] * len(live_rows)):
                            res = ki.kernel.execute(*cols_v)
                            put(r, res)
        return outputs

    @staticmethod
    def _maybe_stack(vals: List[Any]):
        """Stack uniform frame batches into one array so TPU kernels get a
        single device transfer; fall back to lists for ragged/objects."""
        if (vals and isinstance(vals[0], np.ndarray)
                and all(isinstance(v, np.ndarray)
                        and v.shape == vals[0].shape
                        and v.dtype == vals[0].dtype for v in vals)):
            return np.stack(vals)
        return vals
