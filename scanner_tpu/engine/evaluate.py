"""Per-task graph evaluation over batched columns.

Capability parity: reference scanner/engine/evaluate_worker.cpp:408-1328
(EvaluateWorker: row bookkeeping, stencil cache, batching, builtin
sample/space/slice/unslice remapping, per-slice arg rebinding, state reset).

One TaskEvaluator owns the kernel instances of one pipeline instance and
executes tasks end-to-end in column space: {(node_id, column): ColumnBatch}.
A task's frames live in ONE contiguous batch from decode to sink — builtins
are vectorized gathers/relabels on the batch, device kernels receive
on-device slices and chain device-to-device (the reference's pooled
block-allocator + per-call repacking, memory.cpp:269 /
evaluate_worker.cpp:1040-1100, replaced by zero-copy views + a single
host->device transfer per column).

Shape-stable dispatch: XLA compiles one executable per (shape, dtype)
signature, and a TPU compile costs seconds — so device-kernel calls are
routed through a small power-of-two bucket ladder (`bucket_ladder`).  A
tail chunk pads up to the next bucket by edge-repeating its last row
(the REPEAT_EDGE convention stencils already use) and the padding is
sliced off before results are emitted; null-propagated rows ride through
the call at the full chunk shape and are overwritten with NullElement
afterward, so neither task geometry nor null sparsity ever mints a new
executable.  Host/python kernels keep exact shapes (retracing is free
there), and stateful kernels do too (padding rows would advance their
state).  `TaskEvaluator(precompile=...)` warms each device op's ladder
on a background thread — overlapped with the first task's decode — so
steady-state tasks never stall on a compile."""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import (DeviceType, GraphException, JobException, NullElement,
                      ScannerException, SliceList)
from ..graph import analysis as A
from ..graph import fusion as _fusion
from ..graph import ops as O
from ..util import coststats as _cs
from ..util import memstats as _ms
from ..util import metrics as _mx
from ..util import tracing as _tracing
from ..util.log import get_logger
from ..util.profiler import Profiler
from .batch import ColumnBatch, concat_batches, is_array_data

_log = get_logger("evaluate")

# per-op live throughput: fps = delta rows / delta seconds per op label
_M_OP_ROWS = _mx.registry().counter(
    "scanner_tpu_op_rows_total",
    "Rows evaluated per op (kernel calls, warmup rows included).",
    labels=["op"])
_M_OP_SECONDS = _mx.registry().counter(
    "scanner_tpu_op_seconds_total",
    "Wall seconds spent inside each op's kernel calls.",
    labels=["op"])
_M_OP_RECOMPILES = _mx.registry().counter(
    "scanner_tpu_op_recompiles_total",
    "New input (device, shape, dtype) signatures seen per op — each one "
    "forces an XLA recompile of a jitted kernel; a climbing count means "
    "shape churn.  With bucketed dispatch this is bounded by the op's "
    "bucket-ladder size PER CHIP (evaluator affinity compiles each "
    "ladder once per assigned device).",
    labels=["op", "device"])
_M_OP_PAD_ROWS = _mx.registry().counter(
    "scanner_tpu_op_pad_rows_total",
    "Edge-repeat padding rows added by bucketed dispatch to round tail "
    "chunks up to a bucket shape (padding waste; the price of never "
    "re-tracing), per op and assigned device.",
    labels=["op", "device"])
_M_OP_PRECOMPILE = _mx.registry().gauge(
    "scanner_tpu_op_precompile_seconds",
    "Seconds the setup-time warm-up spent precompiling this device "
    "op's bucket ladder on its assigned chip (overlapped with the "
    "first task's decode).",
    labels=["op", "device"])

Elem = Any  # np.ndarray | bytes | arbitrary python object | NullElement
ColKey = Tuple[int, str]  # (node id, column name)


def _is_null(e: Elem) -> bool:
    return isinstance(e, NullElement)


_BACKEND: Optional[str] = None


def _accel_backend() -> bool:
    """True when the default JAX backend is an accelerator.  Device staging
    is pointless (an extra copy) when jax itself runs on host."""
    global _BACKEND
    if _BACKEND is None:
        import jax
        _BACKEND = jax.default_backend()
    return _BACKEND != "cpu"


# ---------------------------------------------------------------------------
# Multi-chip evaluator affinity
# ---------------------------------------------------------------------------
#
# The reference scales by pinning one kernel-group instance per GPU
# (KernelConfig.devices, worker.cpp pipeline instance spin-up); the TPU
# analogue is one pipeline instance per local chip.  Evaluator instance
# *i* owns chip *i mod n_devices*: its stdlib device-kernel calls stage
# inputs to THAT chip (committed jax arrays pull the jitted computation
# onto their device), its bucket-ladder warm-up compiles there, and the
# recompile proxy keys per (device, shape, dtype) so the ladder bound
# holds per chip.  Model kernels keep dp-sharding across the instance's
# device partition (all chips when one instance runs, the reference
# behavior; one chip each when instances == chips).


def _affinity_enabled() -> bool:
    """SCANNER_TPU_DEVICE_AFFINITY=0 restores default-chip dispatch for
    every pipeline instance (the pre-affinity behavior; the multichip
    equivalence tests A/B against it)."""
    return os.environ.get("SCANNER_TPU_DEVICE_AFFINITY", "1") \
        not in ("0", "false")


def kernel_devices() -> Optional[List[Any]]:
    """This host's jax devices, when kernels should see them: always on
    accelerator backends; on the CPU backend only with
    SCANNER_TPU_KERNEL_DEVICES=all, so dryruns/tests exercise the
    multi-chip paths on a virtual multi-device host.  None = kernels run
    wherever jax defaults to (single-device host semantics)."""
    if os.environ.get("SCANNER_TPU_KERNEL_DEVICES") == "all" \
            or _accel_backend():
        import jax
        return list(jax.local_devices())
    return None


def _device_staging_enabled() -> bool:
    """Whether ColumnBatch data is staged onto jax devices for device
    kernels.  On by nature on accelerator backends; the virtual
    multi-device host (SCANNER_TPU_KERNEL_DEVICES=all) opts in so the
    per-chip staging/dispatch paths are testable on CPU."""
    return _accel_backend() \
        or os.environ.get("SCANNER_TPU_KERNEL_DEVICES") == "all"


def assigned_device(instance: int) -> Optional[Any]:
    """The chip pipeline instance `instance` owns — chip `instance mod
    n_devices`, independent of the instance count (instance_devices'
    partitions always lead with this same chip, so the two mappings
    agree for any count) — or None when placement should stay with
    jax's default device (affinity off, host backend without virtual
    devices, or a single chip).  Used by both the evaluator (kernel
    staging/warm-up) and the executor (TaskItem device assignment at
    enqueue time): one mapping, two sides of the handoff."""
    if not _affinity_enabled():
        return None
    devs = kernel_devices()
    if not devs or len(devs) <= 1:
        return None
    return devs[instance % len(devs)]


def instance_devices(instance: int, instances: int = 1
                     ) -> Optional[List[Any]]:
    """Device list instance `instance`'s kernels see (the dp-shard set
    for model kernels).  One instance keeps the whole host's chips
    (today's DataParallelApply behavior); N instances partition them so
    concurrent instances never shard over each other's chips."""
    devs = kernel_devices()
    if not devs:
        return None
    if not _affinity_enabled() or len(devs) <= 1 or instances <= 1:
        return devs
    if instances <= len(devs):
        return devs[instance::instances]
    return [devs[instance % len(devs)]]


def default_pipeline_instances(configured: Optional[int] = None) -> int:
    """Resolve the pipeline-instance count for this node: an explicit
    setting wins — ANY explicit value, including 1 (a user bounding
    memory or serializing evaluation must not be overridden) — and only
    an unset count (None/0) becomes one instance per local chip on
    multi-device hosts (the tentpole default: a v5e-8 worker runs 8
    device-affine instances instead of contending for chip 0), else 1.
    SCANNER_TPU_DEVICE_AFFINITY=0 disables the per-chip resolution."""
    if configured:
        return int(configured)
    devs = kernel_devices() if _affinity_enabled() else None
    if devs and len(devs) > 1:
        return len(devs)
    return 1


# canonical implementation lives with the memory accountant so metrics,
# ledger entries and trace attrs key devices identically; re-exported
# here because the evaluator/executor are its historical home
device_label = _ms.device_label


# ---------------------------------------------------------------------------
# Shape-stable bucketed dispatch
# ---------------------------------------------------------------------------

# smallest bucket: a ladder of {4, 8, ..., cap} bounds the executable
# count at log2(cap/4)+1 while wasting at most 3 padded rows on the
# tiniest call
_MIN_BUCKET = 4


def bucket_ladder(cap: int) -> List[int]:
    """Batch-size buckets for a kernel whose per-call batch cap is `cap`:
    powers of two from min(4, cap) up, with `cap` itself as the top rung
    (so a full chunk never pads).  Every jitted-kernel call shape is one
    of these, so the op compiles at most len(ladder) executables per
    input dtype."""
    cap = max(1, int(cap))
    if cap <= _MIN_BUCKET:
        return [cap]
    ladder = []
    b = _MIN_BUCKET
    while b < cap:
        ladder.append(b)
        b <<= 1
    ladder.append(cap)
    return ladder


def bucket_for(k: int, ladder: Sequence[int]) -> int:
    """Smallest ladder bucket >= k (k must be <= ladder[-1])."""
    for b in ladder:
        if b >= k:
            return b
    return ladder[-1]


def _bucketing_enabled() -> bool:
    """SCANNER_TPU_BUCKETED=0 opts out (exact call shapes, the
    pre-bucketing behavior; padding-equivalence tests A/B against it)."""
    return os.environ.get("SCANNER_TPU_BUCKETED", "1") not in ("0", "false")


def _precompile_enabled() -> bool:
    """Ladder warm-up default: on for accelerator backends (where a cold
    compile stalls the pipeline for seconds), off on the CPU backend
    (retracing is cheap and tests construct many evaluators).
    SCANNER_TPU_PRECOMPILE=1/0 forces either way."""
    flag = os.environ.get("SCANNER_TPU_PRECOMPILE", "")
    if flag in ("0", "false"):
        return False
    if flag in ("1", "force", "true"):
        return True
    return _accel_backend()


def _source_geometry_inputs(node: O.OpNode) -> bool:
    """True when every FRAME input of `node` reaches an Input node
    through builtins only (gathers never change frame geometry), so the
    ladder warm-up's synthesized frames have the source's shape.  An
    intervening kernel (Resize/CropResize/...) may change geometry; its
    consumers skip warm-up rather than compile a wrong-shape ladder —
    and stall their first real call behind it via ensure_warm."""
    for c in node.input_columns():
        if not c.is_frame:
            continue
        p = c.op
        while p.is_builtin and p.name != O.INPUT_OP:
            cols = p.input_columns()
            if not cols:
                return False
            p = cols[0].op
        if p.name != O.INPUT_OP:
            return False
    return True


def _strip_pad(res, k: int, n_out: int):
    """Drop bucket-padding rows from a kernel result before emission.
    Accepts every result protocol emit_result does: a single batch, a
    tuple of per-column batches, or a list of per-row results/tuples."""
    if n_out > 1 and isinstance(res, tuple) and len(res) == n_out:
        return tuple(r[:k] for r in res)
    try:
        return res[:k]
    except TypeError:
        return res  # malformed result: let emit_result raise its error


class StateCarryMiss(Exception):
    """A carry plan's premise failed: the kernel instance's state is not
    positioned at the plan's watermark (task reordering, a failed
    predecessor, or a different instance).  The executor catches this and
    re-runs the task with a self-contained plan — affinity is a pure
    optimization, never a correctness dependency."""


class KernelInstance:
    """One live kernel with its stream/state bookkeeping."""

    def __init__(self, node: O.OpNode, profiler: Profiler,
                 devices: Optional[List[Any]] = None,
                 device: Optional[Any] = None):
        assert node.spec is not None and node.spec.kernel_factory is not None
        self.node = node
        self.spec = node.spec
        cfg = O.KernelConfig(device=node.effective_device(),
                             args=dict(node.init_args),
                             devices=devices or [])
        # canonical class identity: an unpickled job spec can carry a
        # cloudpickle by-value class COPY of a locally-registered op;
        # instantiating the registered original keeps class-level state
        # (and identity-sensitive tests) on one class object
        factory = O.registry.canonical_factory(self.spec)
        self.kernel = factory(cfg, **node.init_args)
        self.profiler = profiler
        # the chip this instance's calls are pinned to (evaluator
        # affinity); None = jax default placement.  Committed inputs on
        # this device pull the shared jitted functions onto it.
        self.device = device
        self.dev_label = device_label(device)
        self._cur_stream: Tuple[int, int] = (-1, -1)  # (job, slice group)
        self._last_row: Optional[int] = None
        self._did_setup = False
        # input (shape, dtype) signatures already executed (XLA recompile
        # proxy — dtype included: equal shapes with different dtypes are
        # distinct executables)
        self._shape_sigs: set = set()
        # bucket-ladder warm-up state: idle (not scheduled) | pending
        # (scheduled, not started) | running | done
        self._warm_lock = threading.Lock()
        self._warm_state = "idle"
        self._warm_done = threading.Event()
        # serializes kernel.execute between the evaluation thread and a
        # warm-up/re-warm thread: two concurrent execute() calls on one
        # kernel instance are not guaranteed safe, and the ensure_warm
        # handshake alone cannot cover a MID-RUN rewarm (the
        # recompile_storm remediation).  Uncontended in steady state.
        self._call_lock = threading.Lock()

    def setup(self, fetch: bool = True) -> None:
        if not self._did_setup:
            if fetch:
                self.kernel.fetch_resources()
            self.kernel.setup_with_resources()
            self._did_setup = True

    def stream_args(self, job_idx: int, slice_group: int) -> dict:
        """The per-stream kwargs new_stream would receive for this
        (job, slice group) — also the trace-affecting part of a fused
        chain's program key (e.g. Resize bakes width/height into the
        jitted body at trace time)."""
        args = {}
        for name, per_stream in self.node.job_args.items():
            if name not in self.spec.stream_arg_names:
                continue
            v = per_stream[job_idx]
            if isinstance(v, SliceList):
                v = v[slice_group]
            args[name] = v
        return args

    def bind_stream(self, job_idx: int, slice_group: int) -> None:
        """Call new_stream when the (job, slice group) changes
        (reference evaluate_worker.cpp:640-707 per-slice arg rebinding)."""
        key = (job_idx, slice_group)
        if key == self._cur_stream:
            return
        self.kernel.new_stream(**self.stream_args(job_idx, slice_group))
        self.kernel.reset()
        self._cur_stream = key
        self._last_row = None

    def maybe_reset(self, row: int) -> None:
        """Reset state at row discontinuities (the reference kernel checks
        element indices itself, test_ops.cpp:183-189; we centralize it)."""
        if self._last_row is not None and row != self._last_row + 1 \
                and self.spec.is_stateful:
            self.kernel.reset()
        self._last_row = row

    # -- bucket-ladder warm-up (precompile) ----------------------------

    def _example_args(self, b: int, h: int, w: int) -> Optional[List[Any]]:
        """Synthesized execute() args at batch size `b` for warm-up:
        frame columns get (b[, W], h, w, 3) uint8 zeros, non-frame
        columns come from the kernel's optional `precompile_input(name)`
        hook.  None = this op is not generically warmable (variadic, or
        a non-frame input without a hook)."""
        if self.spec.variadic:
            return None
        sten = self.node.effective_stencil()
        win = len(sten) if sten != [0] else 0
        args: List[Any] = []
        for name, is_frame in self.spec.input_columns:
            if is_frame:
                shape = (b, win, h, w, 3) if win else (b, h, w, 3)
                args.append(np.zeros(shape, np.uint8))
            else:
                hook = getattr(self.kernel, "precompile_input", None)
                row = hook(name) if hook is not None else None
                if row is None:
                    return None
                args.append([[row] * win for _ in range(b)] if win
                            else [row] * b)
        return args

    def precompile(self, ladder: Sequence[int], h: int, w: int) -> None:
        """Compile this kernel's jitted function at every ladder bucket
        (best-effort: a failing warm-up shape is skipped; the real call
        then compiles it).  Runs on the evaluator's warm-up thread;
        ensure_warm() on the evaluation thread claims or waits."""
        with self._warm_lock:
            if self._warm_state != "pending":
                return  # claimed by a real call racing ahead of us
            self._warm_state = "running"
        t0 = time.time()
        try:
            for b in ladder:
                args = self._example_args(b, h, w)
                if args is None:
                    return
                if self.device is not None:
                    # warm THIS instance's chip: committed example
                    # inputs compile the ladder executable for the
                    # device the real calls will run on (the persistent
                    # compilation cache dedups the XLA work across
                    # same-kind chips)
                    import jax
                    staged = []
                    for a in args:
                        if isinstance(a, np.ndarray):
                            a = jax.device_put(a, self.device)
                            # ledger: warm-up args hold HBM until this
                            # bucket's compile finishes (released when
                            # the arrays are collected at loop exit)
                            _ms.track_array(a, "warmup",
                                            device=self.dev_label)
                        staged.append(a)
                    args = staged
                try:
                    # compile ledger: the warm-up compile of this
                    # ladder rung is attributed to (op, device, bucket)
                    # — with the persistent cache configured, a warmed
                    # restart records it as a `hit`
                    with self._call_lock, \
                            _cs.observe_compiles(self.node.name,
                                                 self.dev_label, b,
                                                 f"warmup:b{b}"):
                        self.kernel.execute(*args)
                except Exception:  # noqa: BLE001 — warm-up is best-effort
                    _log.debug("precompile of %s at batch %d failed",
                               self.node.name, b, exc_info=True)
                    return
            _M_OP_PRECOMPILE.labels(op=self.node.name,
                                    device=self.dev_label).set(
                time.time() - t0)
        finally:
            with self._warm_lock:
                self._warm_state = "done"
            self._warm_done.set()

    def ensure_warm(self) -> None:
        """Called before a real execute(): if this kernel's warm-up is
        mid-flight, wait for it (two concurrent execute() calls on one
        kernel instance are not guaranteed safe); if it has not started
        yet, claim it so the warm-up thread skips this kernel."""
        with self._warm_lock:
            if self._warm_state == "pending":
                self._warm_state = "done"
                self._warm_done.set()
                return
            if self._warm_state != "running":
                return
        self._warm_done.wait()

    def close(self) -> None:
        self.kernel.close()


# shared fused-chain programs, keyed on everything that affects the
# trace: member op identity, init args, stream-bound args, and window
# layout.  Evaluators are constructed per task on the non-pipelined
# path, so a per-instance jax.jit closure would recompile the chain
# every task while staged members amortize through their module-level
# @jax.jit impls — this cache gives chains the same amortization.
# Entries own FROZEN kernel objects built from the node spec (never
# the live evaluator's kernels: those rebind stream args, and a
# later retrace through a mutated kernel would poison the entry).
_CHAIN_PROGRAMS: Dict[Tuple, Any] = {}
_CHAIN_PROGRAMS_LOCK = threading.Lock()


def _build_chain_program(nodes: List[O.OpNode],
                         stream_args: List[dict],
                         windows: List[int]):
    """One jitted callable for a chain: cache-owned kernels constructed
    from the canonical factories, stream-bound once, composed
    head->tail inside a single trace."""
    import jax
    kernels = []
    for node, sargs in zip(nodes, stream_args):
        factory = O.registry.canonical_factory(node.spec)
        cfg = O.KernelConfig(device=node.effective_device(),
                             args=dict(node.init_args), devices=[])
        k = factory(cfg, **node.init_args)
        k.fetch_resources()
        k.setup_with_resources()
        if sargs:
            k.new_stream(**sargs)
        k.reset()
        kernels.append(k)

    def chain_fn(y):
        for k, win in zip(kernels, windows):
            if win:
                y = y.reshape((y.shape[0] // win, win)
                              + tuple(y.shape[1:]))
            y = k.execute_traced(y)
        return y

    return jax.jit(chain_fn)


class FusedKernelInstance:
    """One planned fusion chain (graph/fusion.py) compiled as a SINGLE
    jitted program: the member kernels' `execute_traced` bodies compose
    inside one trace, so XLA fuses across op boundaries and member
    intermediates never materialize in HBM (they only exist as values
    inside the fused executable).  The chain dispatches at its TAIL
    node with ONE bucket ladder for the whole chain — per (device,
    shape, dtype) signature the chain mints ONE executable where the
    staged path minted len(chain).

    Mirrors KernelInstance's warm-up/call-lock protocol so the
    evaluator's precompile thread, ensure_warm handshake, and the
    recompile_storm rewarm path treat chains and single kernels
    uniformly.  All attribution (recompile proxy, pad rows, compile
    ledger, op rows/seconds, roofline) keys on the stable chain id
    `"a+b+c"` — member names joined head to tail."""

    def __init__(self, chain: "_fusion.FusionChain",
                 members: List[KernelInstance]):
        self.chain = chain
        self.members = members
        self.chain_id = chain.chain_id
        self.member_names = chain.member_names
        self.head = members[0]
        self.tail = members[-1]
        # all members share this evaluator instance's assigned chip
        # (the planner only fuses same-effective-device TPU runs, and
        # the evaluator pins every TPU kernel to its own chip)
        self.device = self.tail.device
        self.dev_label = self.tail.dev_label
        # per member, head->tail: window length (0 = no window axis,
        # matching _example_args' convention for stencil == [0])
        self.windows = chain.windows()
        self.stencils = [np.asarray(s, np.int64) for s in chain.stencils()]
        self.width = chain.width()
        self._jit = None
        # current stream-bound args per member (set by bind_stream);
        # part of the shared-program key — a stream rebind that changes
        # them must map to a different compiled program
        self._stream_args: Optional[List[dict]] = None
        self._shape_sigs: set = set()
        # (shape, dtype) -> (chain CostDescriptor | None, bytes of
        # member intermediates the fusion avoided materializing)
        self._cost_cache: Dict[Tuple, Tuple] = {}
        self._warm_lock = threading.Lock()
        self._warm_state = "idle"
        self._warm_done = threading.Event()
        self._call_lock = threading.Lock()

    # -- the fused program ---------------------------------------------

    def _chain_fn(self, y):
        """The whole chain as one traceable function: (k * width, ...)
        composed-window gather of the head's input in, the tail's raw
        traced result out.  Each windowed member folds its own window
        axis out of the composed leading dimension — the composed
        gather (compose_positions) laid positions out with the HEAD's
        window innermost, so the progressive reshape walks the nesting
        exactly."""
        for ki, win in zip(self.members, self.windows):
            if win:
                y = y.reshape((y.shape[0] // win, win)
                              + tuple(y.shape[1:]))
            y = ki.kernel.execute_traced(y)
        return y

    def _fn(self):
        if self._jit is not None:
            return self._jit
        if self._stream_args is not None:
            key = tuple(
                (ki.spec.name,
                 f"{type(ki.kernel).__module__}."
                 f"{type(ki.kernel).__qualname__}",
                 repr(sorted(ki.node.init_args.items())),
                 repr(sorted(sargs.items())), win)
                for ki, sargs, win in zip(self.members,
                                          self._stream_args,
                                          self.windows))
            with _CHAIN_PROGRAMS_LOCK:
                fn = _CHAIN_PROGRAMS.get(key)
            if fn is None:
                try:
                    fn = _build_chain_program(
                        [ki.node for ki in self.members],
                        self._stream_args, self.windows)
                except Exception:  # noqa: BLE001 — fall back per instance
                    _log.debug("shared program build failed for chain "
                               "%s", self.chain_id, exc_info=True)
                    fn = None
                if fn is not None:
                    with _CHAIN_PROGRAMS_LOCK:
                        fn = _CHAIN_PROGRAMS.setdefault(key, fn)
            if fn is not None:
                self._jit = fn
                return fn
        import jax
        self._jit = jax.jit(self._chain_fn)
        return self._jit

    def execute(self, arr):
        """One fused call: jitted chain body, then the tail's host-side
        finish() outside the trace (the staged path's post-jit tail)."""
        return self.tail.kernel.finish(self._fn()(arr))

    def bind_stream(self, job_idx: int, slice_group: int) -> None:
        sargs = []
        for ki in self.members:
            ki.bind_stream(job_idx, slice_group)
            sargs.append(ki.stream_args(job_idx, slice_group))
        if sargs != self._stream_args:
            self._stream_args = sargs
            self._jit = None

    def compose_positions(self, rows: np.ndarray, max_in: int) -> np.ndarray:
        """Head-input read positions for tail compute rows `rows`: the
        member stencils composed tail-first, REPEAT_EDGE-clamped at
        EVERY level — exactly the staged pipeline's transitive backward
        dilation (graph/analysis.py derive_task_streams), so the fused
        gather reads precisely the rows the staged members would have.
        Returns a flat (len(rows) * width,) position array."""
        pos = np.asarray(rows, np.int64)
        for sten, win in zip(reversed(self.stencils),
                             reversed(self.windows)):
            if win:
                pos = np.clip(pos[:, None] + sten[None, :], 0,
                              max_in - 1).reshape(-1)
        return pos

    # -- chain cost model ----------------------------------------------

    def cost_for(self, shape, dtype):
        """Analytical chain descriptor for a head-input signature:
        member costs summed via stepwise shape inference
        (jax.eval_shape walks the chain without running it), with
        bytes_in/bytes_out taken at the chain BOUNDARY — the fused
        program touches HBM only there.  Also returns the member
        intermediate bytes fusion avoided (every non-tail output +
        every non-head input stays on-chip).  Cached per signature;
        (None, 0.0) when any member lacks a cost model."""
        key = (tuple(shape), str(dtype))
        hit = self._cost_cache.get(key)
        if hit is not None:
            return hit
        desc, saved = None, 0.0
        try:
            import jax
            aval = jax.ShapeDtypeStruct(tuple(shape), dtype)
            descs = []
            last = len(self.members) - 1
            for i, (ki, win) in enumerate(zip(self.members, self.windows)):
                shp = tuple(aval.shape)
                if win:
                    shp = (shp[0] // win, win) + shp[1:]
                    aval = jax.ShapeDtypeStruct(shp, aval.dtype)
                d = ki.kernel.cost([shp])
                if isinstance(d, dict):
                    d = _cs.CostDescriptor(**d)
                descs.append(d)
                if i < last:
                    aval = jax.eval_shape(ki.kernel.execute_traced, aval)
            if descs and all(d is not None for d in descs):
                desc = _cs.CostDescriptor(
                    flops=sum(float(d.flops or 0.0) for d in descs),
                    bytes_in=descs[0].bytes_in,
                    bytes_out=descs[-1].bytes_out,
                    source="hook")
                saved = (sum(float(d.bytes_out or 0.0)
                             for d in descs[:-1])
                         + sum(float(d.bytes_in or 0.0)
                               for d in descs[1:]))
        except Exception:  # noqa: BLE001 — cost attribution is optional
            _log.debug("chain cost model failed for %s", self.chain_id,
                       exc_info=True)
        self._cost_cache[key] = (desc, saved)
        return desc, saved

    # -- chain batch cap / warm-up -------------------------------------

    def cap_for(self, wp: Optional[int]) -> int:
        """Per-call batch cap for the CHAIN: walking tail->head, member
        i runs at (tail rows x its downstream window expansion) rows
        per call, so its own cap (the same work-packet derivation as
        _run_kernel) divides down by that expansion.  The chain takes
        the tightest bound — no member ever sees a call larger than it
        would have accepted staged."""
        cap = None
        exp = 1
        for ki, win in zip(reversed(self.members),
                           reversed(self.windows)):
            n = ki.node
            if n.batch is None and wp:
                mcap = max(1, min(n.effective_batch(), int(wp)))
            else:
                mcap = max(1, n.effective_batch())
            c = max(1, mcap // max(1, exp))
            cap = c if cap is None else min(cap, c)
            exp *= max(win, 1)
        return max(1, cap if cap is not None else 1)

    def warmable(self) -> bool:
        """Generic warm-up synthesizes head frames at source geometry:
        needs a frame head input reachable from Input through builtins
        only (same eligibility as single-kernel warm-up)."""
        n = self.head.node
        return bool(n.spec.input_columns
                    and n.spec.input_columns[0][1]
                    and _source_geometry_inputs(n))

    def precompile(self, ladder: Sequence[int], h: int, w: int) -> None:
        """Compile the fused program at every chain-ladder bucket (one
        ladder for the WHOLE chain — this is the warm-up the staged
        path would have run once per member)."""
        with self._warm_lock:
            if self._warm_state != "pending":
                return
            self._warm_state = "running"
        t0 = time.time()
        try:
            # bind job-0 stream args first: members like Resize get
            # their geometry from new_stream, and an unbound warm-up
            # would compile a degenerate (e.g. 0x0-output) program the
            # real calls never use.  The real dispatch rebinds only if
            # its (job, slice group) differs, so the warmed executable
            # survives into the first call.
            try:
                self.bind_stream(0, 0)
            except Exception:  # noqa: BLE001 — warm-up is best-effort
                _log.debug("warm-up stream bind failed for chain %s",
                           self.chain_id, exc_info=True)
            for b in ladder:
                arr = np.zeros((b * self.width, h, w, 3), np.uint8)
                if self.device is not None:
                    import jax
                    arr = jax.device_put(arr, self.device)
                    _ms.track_array(arr, "warmup", device=self.dev_label)
                try:
                    with self._call_lock, \
                            _cs.observe_compiles(self.chain_id,
                                                 self.dev_label, b,
                                                 f"warmup:b{b}",
                                                 members=self.member_names):
                        self.execute(arr)
                except Exception:  # noqa: BLE001 — warm-up is best-effort
                    _log.debug("precompile of chain %s at batch %d "
                               "failed", self.chain_id, b, exc_info=True)
                    return
            _M_OP_PRECOMPILE.labels(op=self.chain_id,
                                    device=self.dev_label).set(
                time.time() - t0)
        finally:
            with self._warm_lock:
                self._warm_state = "done"
            self._warm_done.set()

    def ensure_warm(self) -> None:
        """Same handshake as KernelInstance.ensure_warm."""
        with self._warm_lock:
            if self._warm_state == "pending":
                self._warm_state = "done"
                self._warm_done.set()
                return
            if self._warm_state != "running":
                return
        self._warm_done.wait()


# every live TaskEvaluator, weakly held: the recompile_storm
# remediation playbook (engine/controller.py) re-warms bucket ladders
# process-wide through rewarm_all() without owning evaluator lifetimes
_LIVE_EVALUATORS: "weakref.WeakSet" = weakref.WeakSet()


def rewarm_all() -> int:
    """Re-schedule the bucket-ladder warm-up on every live evaluator
    (the recompile_storm -> ladder_rewarm remediation action).
    Returns the total number of kernels scheduled; best-effort — an
    evaluator failing to re-warm never raises out of the actuator."""
    total = 0
    for te in list(_LIVE_EVALUATORS):
        try:
            total += te.rewarm()
        except Exception:  # noqa: BLE001 — remediation is best-effort
            _log.exception("ladder re-warm failed for an evaluator")
    return total


class TaskEvaluator:
    def __init__(self, info: A.GraphInfo, profiler: Profiler,
                 devices: Optional[List[Any]] = None,
                 skip_fetch_resources: bool = False,
                 precompile: Optional[Tuple[int, int, int]] = None,
                 instance: int = 0, instances: int = 1):
        self.info = info
        self.profiler = profiler
        # device affinity: this pipeline instance owns ONE chip (instance
        # i of P -> chip i mod n); all its stdlib device-kernel calls
        # stage and run there.  `devices` (the dp-shard set for model
        # kernels — models/infer.py DataParallelApply) defaults to this
        # instance's partition of the host's chips: the whole host for a
        # single instance (the reference's one-GPU-per-instance pinning,
        # adapted), a disjoint slice each when instances run per chip.
        # SCANNER_TPU_KERNEL_DEVICES=all extends both to the CPU backend
        # so dryruns/tests exercise them on a virtual multi-device host.
        self.instance = instance
        self.device = assigned_device(instance)
        if devices is None:
            devices = instance_devices(instance, instances)
        self.kernels: Dict[int, KernelInstance] = {}
        for n in info.ops:
            if not n.is_builtin:
                # only device-placed kernels get the chip list: a kernel
                # explicitly pinned to CPU must not dp-shard onto TPU
                n_devs = devices \
                    if n.effective_device() == DeviceType.TPU else None
                ki = KernelInstance(
                    n, profiler, n_devs,
                    device=self.device
                    if n.effective_device() == DeviceType.TPU else None)
                self.kernels[n.id] = ki
        for ki in self.kernels.values():
            ki.setup(fetch=not skip_fetch_resources)
        # whole-pipeline fusion (graph/fusion.py): maximal runs of
        # fusable consecutive device ops execute as ONE jitted program.
        # Non-tail members never dispatch (or materialize an output
        # column) on their own — the tail node runs the whole chain.
        self.chains: Dict[int, "_fusion.FusionChain"] = {}
        self.fused: Dict[int, FusedKernelInstance] = {}
        self._chain_member_ids: set = set()
        if _fusion.enabled():
            for ch in _fusion.plan_chains(info):
                self.chains[ch.tail.id] = ch
                self.fused[ch.tail.id] = FusedKernelInstance(
                    ch, [self.kernels[m.id] for m in ch.members])
                for m in ch.members[:-1]:
                    self._chain_member_ids.add(m.id)
        # bucket-ladder warm-up: compile every device op's ladder shapes
        # on a background thread so the compiles overlap the first
        # task's decode instead of stalling its evaluation.  `precompile`
        # is a (frame_h, frame_w, work_packet_size) hint from the
        # executor (engine geometry is not knowable from the graph
        # alone); evaluation threads join per-kernel via ensure_warm().
        self._precompile_thread: Optional[threading.Thread] = None
        self._precompile_hint = precompile
        if precompile is not None and _precompile_enabled() \
                and _bucketing_enabled():
            targets = self._warm_targets(precompile)
            for ki, _ladder in targets:
                ki._warm_state = "pending"
            self._spawn_warm(targets, precompile)
        # live-evaluator registry: the recompile_storm remediation
        # (engine/controller.py -> rewarm_all) re-schedules ladder
        # warm-ups on whatever evaluators currently exist; weak so a
        # closed/forgotten evaluator never pins its kernels alive
        _LIVE_EVALUATORS.add(self)

    def _warm_targets(self, precompile: Tuple[int, int, int]
                      ) -> List[Tuple[Any, List[int]]]:
        """The warm-up-eligible kernels and their ladders (shared by
        the constructor warm-up and rewarm)."""
        _h, _w, wp = precompile
        targets: List[Tuple[Any, List[int]]] = []
        for ki in self.kernels.values():
            n = ki.node
            if n.id in self._chain_member_ids or n.id in self.chains:
                continue  # fused members warm as one chain, below
            if n.effective_device() != DeviceType.TPU \
                    or n.effective_batch() <= 1 \
                    or ki.spec.is_stateful or ki.spec.variadic \
                    or not _source_geometry_inputs(n):
                continue
            # same per-call cap derivation as _run_kernel
            if n.batch is None and wp:
                cap = max(1, min(n.effective_batch(), int(wp)))
            else:
                cap = max(1, n.effective_batch())
            targets.append((ki, bucket_ladder(cap)))
        # fused chains warm their ONE chain ladder (precompile is
        # polymorphic over KernelInstance / FusedKernelInstance)
        for fki in self.fused.values():
            if fki.warmable():
                targets.append((fki, bucket_ladder(fki.cap_for(wp))))
        return targets

    def _spawn_warm(self, targets, precompile) -> None:
        if not targets:
            return
        h, w, _wp = precompile

        def warm() -> None:
            for ki, ladder in targets:
                ki.precompile(ladder, h, w)

        self._precompile_thread = threading.Thread(
            target=warm, name="precompile", daemon=True)
        self._precompile_thread.start()

    def rewarm(self) -> int:
        """Re-schedule the bucket-ladder warm-up (the recompile_storm
        remediation): kernels whose warm-up is idle or done go back to
        pending and a fresh warm-up thread re-executes their ladders —
        with the persistent compilation cache configured this re-pins
        executables at cache-hit cost.  Mid-flight warm-ups and claims
        by racing real calls are respected (the same
        ensure_warm/_call_lock handshake as construction).  Returns
        the number of kernels scheduled."""
        hint = self._precompile_hint
        if hint is None or not _precompile_enabled() \
                or not _bucketing_enabled():
            return 0
        claimed: List[Tuple[Any, List[int]]] = []
        for ki, ladder in self._warm_targets(hint):
            with ki._warm_lock:
                if ki._warm_state in ("idle", "done"):
                    ki._warm_state = "pending"
                    ki._warm_done.clear()
                    claimed.append((ki, ladder))
        self._spawn_warm(claimed, hint)
        return len(claimed)

    def close(self) -> None:
        _LIVE_EVALUATORS.discard(self)
        for ki in self.kernels.values():
            ki.close()

    # ------------------------------------------------------------------

    def execute_task(self, jr: A.JobRows, plan: A.TaskPlan,
                     source_batches: Dict[int, ColumnBatch]
                     ) -> Dict[int, ColumnBatch]:
        """Run one task.  source_batches: Input node id -> ColumnBatch.
        Returns sink node id -> ColumnBatch of output rows."""
        store: Dict[ColKey, ColumnBatch] = {}
        results: Dict[int, ColumnBatch] = {}
        # remaining column-reads per producer: a column is dropped from the
        # store the moment its last consumer has run, so peak host/device
        # memory is the live frontier, not every intermediate of the task
        # (the reference streams work packets through stages instead,
        # worker.cpp stage drivers; with batched columns, freeing eagerly
        # achieves the same bound per io-packet)
        remaining = {nid: len(lst)
                     for nid, lst in self.info.consumers.items()}
        self.last_peak_columns = 0

        for n in self.info.ops:
            if n.id in self._chain_member_ids:
                # fused into a chain: the tail node dispatches the whole
                # chain, this member never materializes an output column
                continue
            ts = plan.streams[n.id]
            if n.name == O.INPUT_OP:
                store[(n.id, "output")] = source_batches[n.id]
            elif n.name in (O.SAMPLE_OP, O.SPACE_OP):
                store[(n.id, "output")] = self._run_sampler(n, jr, plan, store)
            elif n.name == O.SLICE_OP:
                store[(n.id, "output")] = self._run_slice(n, jr, plan, store)
            elif n.name == O.UNSLICE_OP:
                store[(n.id, "output")] = self._run_unslice(n, jr, plan, store)
            elif n.name == O.OUTPUT_OP:
                src = n.input_columns()[0]
                results[n.id] = store[(src.op.id, src.column)].take_rows(
                    ts.valid_output_rows)
            elif n.id in self.chains:
                outs = self._run_fused(n, jr, plan, store)
                for col, b in outs.items():
                    store[(n.id, col)] = b
            else:
                outs = self._run_kernel(n, jr, plan, store)
                for col, b in outs.items():
                    store[(n.id, col)] = b
            self.last_peak_columns = max(self.last_peak_columns, len(store))
            if n.id in self.chains:
                # the whole chain's input edges are consumed here: the
                # head's (and every member's) reads happen at tail time,
                # and member columns themselves were never stored
                cons_cols = [c for m in self.chains[n.id].members
                             for c in m.input_columns()]
            else:
                cons_cols = n.input_columns()
            for c in cons_cols:
                pid = c.op.id
                remaining[pid] -= 1
                if remaining[pid] == 0:
                    for key in [k for k in store if k[0] == pid]:
                        del store[key]
        return results

    # -- builtins (vectorized gathers on the batch) ---------------------

    def _input_batch(self, n: O.OpNode, store) -> ColumnBatch:
        src = n.input_columns()[0]
        return store[(src.op.id, src.column)]

    def _run_sampler(self, n, jr, plan, store) -> ColumnBatch:
        ts = plan.streams[n.id]
        g = plan.slice_group if self.info.slice_level[n.id] > 0 else 0
        sampler = jr.samplers[n.id][g]
        in_b = self._input_batch(n, store)
        up_rows = ts.valid_input_rows
        down_rows, mapping = sampler.downstream_map(up_rows)
        need = np.asarray(ts.valid_output_rows, np.int64)
        pos_in_down = {int(d): i for i, d in enumerate(down_rows.tolist())}
        try:
            sel = np.array([pos_in_down[int(d)] for d in need.tolist()],
                           np.int64)
        except KeyError:
            missing = sorted(set(need.tolist()) - pos_in_down.keys())
            raise JobException(
                f"{n.name}: missing output rows {missing[:5]}...")
        m_sel = np.asarray(mapping, np.int64)[sel] if len(sel) else sel
        if not len(up_rows) or (m_sel < 0).all():
            return ColumnBatch.from_elements(
                need, [NullElement()] * len(need))
        src_rows = up_rows[np.maximum(m_sel, 0)]
        positions = in_b.positions(np.asarray(src_rows, np.int64))
        positions = np.where(m_sel < 0, -1, positions)
        return in_b.take(positions, need)

    def _run_slice(self, n, jr, plan, store) -> ColumnBatch:
        ts = plan.streams[n.id]
        group = jr.partitioners[n.id].group_at(plan.slice_group)
        in_b = self._input_batch(n, store)
        need = np.asarray(ts.valid_output_rows, np.int64)
        src = np.asarray(group, np.int64)[need]
        return in_b.take(in_b.positions(src), need)

    def _run_unslice(self, n, jr, plan, store) -> ColumnBatch:
        ts = plan.streams[n.id]
        inp = n.input_columns()[0].op
        offset = int(np.concatenate(
            [[0], np.cumsum(jr.rows[inp.id])])[plan.slice_group])
        in_b = self._input_batch(n, store)
        need = np.asarray(ts.valid_output_rows, np.int64)
        return in_b.take(in_b.positions(need - offset), need)

    # -- regular kernels -----------------------------------------------

    def _run_kernel(self, n: O.OpNode, jr: A.JobRows, plan: A.TaskPlan,
                    store) -> Dict[str, ColumnBatch]:
        ts = plan.streams[n.id]
        ki = self.kernels[n.id]
        ki.bind_stream(plan.job_idx, plan.slice_group)

        in_cols = n.input_columns()
        in_batches = [store[(c.op.id, c.column)] for c in in_cols]
        g = plan.slice_group if self.info.slice_level[n.id] > 0 else 0
        in_op = in_cols[0].op
        max_in = jr.rows[in_op.id][g]
        stencil = n.effective_stencil()
        has_stencil = stencil != [0]
        # The batch DECLARATION fixes the calling convention (batched
        # kernels always receive row batches, even 1-row ones) and CAPS
        # the per-call batch (ops declare it as a memory bound); within
        # that cap, PerfParams.work_packet_size sets the chunk — the XLA
        # batch dimension (reference io/work packet split, master.cpp:1421)
        # — unless the op was constructed with an explicit batch= override.
        batched_call = n.effective_batch() > 1
        if batched_call and n.batch is None:
            batch = max(1, min(n.effective_batch(),
                               int(getattr(jr, "work_packet_size",
                                           n.effective_batch()))))
        else:
            batch = max(1, n.effective_batch())

        # Shape-stable dispatch: device-placed batched kernels wrap
        # jitted functions that compile one executable per (shape,
        # dtype), on ANY backend — so their calls are rounded up to a
        # small bucket ladder (pad by edge-repeating the last row, slice
        # the padding off after).  Host/python kernels keep exact shapes
        # (retracing is free), and so do stateful kernels: padding rows
        # would advance their state past the real stream position.
        use_buckets = (batched_call and not n.spec.is_stateful
                       and n.effective_device() == DeviceType.TPU
                       and _bucketing_enabled())
        ladder = bucket_ladder(batch) if use_buckets else None

        # Device staging: a device kernel gets its inputs moved host->device
        # ONCE per task column (async, whole batch); a host kernel gets
        # device inputs fetched once.  Updated in the store so sibling
        # consumers of the same column reuse the placement.  The target is
        # THIS instance's assigned chip: committed inputs pull the shared
        # jitted kernel functions onto it, and a batch the loader
        # pre-staged for this instance is already there (to_device no-ops
        # instead of silently copying cross-chip).
        is_device_kernel = (n.effective_device() == DeviceType.TPU
                            and _device_staging_enabled())
        for i, (c, b) in enumerate(zip(in_cols, in_batches)):
            if is_device_kernel and isinstance(b.data, np.ndarray) \
                    and b.data.dtype != object:
                b = b.to_device(ki.device)
            elif not is_device_kernel:
                b = b.to_host()
            # resolve a pending wire-format conversion (YUV420 staged at
            # 1.5 B/px) exactly once, where the data now lives: a jit
            # device op for device kernels — XLA fuses it ahead of the
            # kernel — or the bit-identical numpy flavor on host
            if b.convert is not None:
                b = b.converted()
            in_batches[i] = b
            store[(c.op.id, c.column)] = b

        compute = np.asarray(ts.compute_rows, np.int64)
        out_cols = [c for c, _ in n.spec.output_columns]
        valid_out = np.asarray(ts.valid_output_rows, np.int64)
        valid_set = set(valid_out.tolist())

        # A carry plan (unbounded-state node whose recompute starts past
        # row 0) is only sound if THIS kernel instance's state sits
        # exactly at the preceding row of the same stream; anything else
        # (reordered tasks, a failed predecessor, another instance) and
        # maybe_reset would silently reset mid-stream — wrong results.
        # Fail to the self-contained fallback instead.
        # (bind_stream above already rebound+reset on any stream change,
        # nulling _last_row — so the position check alone covers foreign
        # streams, reordering, and failed predecessors)
        if n.spec.unbounded_state and len(compute) and int(compute[0]) > 0:
            if ki._last_row != int(compute[0]) - 1:
                raise StateCarryMiss(
                    f"{n.name}: carry plan expects state at row "
                    f"{int(compute[0]) - 1} of stream "
                    f"({plan.job_idx}, {plan.slice_group}); instance is "
                    f"at {ki._last_row}")

        # window positions per compute row per input column (REPEAT_EDGE)
        sten = np.asarray(stencil, np.int64)
        win_rows = np.clip(compute[:, None] + sten[None, :], 0, max_in - 1)
        col_pos = [b.positions(win_rows.reshape(-1)).reshape(win_rows.shape)
                   for b in in_batches]

        # null propagation: a row whose inputs (or stencil window) contain a
        # null yields null without running the kernel
        null_in = np.zeros(len(compute), bool)
        for b, pos in zip(in_batches, col_pos):
            if b.nulls is not None:
                null_in |= b.nulls[pos].any(axis=1)

        # Under bucketed dispatch a sparse null must not shrink the call
        # shape (every distinct "live subset" size would mint an
        # executable): run the FULL chunk and overwrite dead rows with
        # NullElement afterward.  Safe only when every nulled input is
        # array data (null positions hold valid zero rows); an object
        # column holds NullElement objects the kernel would choke on, so
        # those rare chunks call on the live subset — still padded up to
        # a bucket below, so shapes stay ladder-bounded either way.
        mask_nulls = use_buckets and all(
            b.nulls is None or is_array_data(b.data) for b in in_batches)

        # contiguous runs of compute rows; reset state between runs
        run_bounds: List[Tuple[int, int]] = []
        start = 0
        for i in range(1, len(compute) + 1):
            if i == len(compute) or compute[i] != compute[i - 1] + 1:
                run_bounds.append((start, i))
                start = i
        out_parts: Dict[str, List[ColumnBatch]] = {c: [] for c in out_cols}

        def emit(col: str, rows: np.ndarray, data, per_row: bool) -> None:
            """Append kernel results, dropping warmup rows."""
            keep = np.isin(rows, valid_out)
            if not keep.any():
                return
            if per_row:
                kept = [d for d, k in zip(data, keep) if k]
                out_parts[col].append(
                    ColumnBatch.from_elements(rows[keep], kept))
            else:
                if keep.all():
                    out_parts[col].append(ColumnBatch(rows, data))
                else:
                    idx = np.flatnonzero(keep)
                    out_parts[col].append(
                        ColumnBatch(rows[keep], data[idx]))

        def emit_result(rows: np.ndarray, res) -> None:
            """Dispatch one kernel call's result to output columns.

            Multi-output batch kernels may return either a tuple of
            per-column batches or a list of per-row tuples (the classic
            protocol) — both are accepted."""
            if len(out_cols) == 1:
                cols_res = (res,)
            elif isinstance(res, tuple) and len(res) == len(out_cols):
                cols_res = res
            elif (isinstance(res, list) and len(res) == len(rows)
                  and all(isinstance(r, tuple) and len(r) == len(out_cols)
                          for r in res)):
                cols_res = tuple(list(col) for col in zip(*res))
            else:
                raise JobException(
                    f"{n.name}: expected {len(out_cols)}-tuple output")
            for col, r in zip(out_cols, cols_res):
                if is_array_data(r) and len(r) == len(rows):
                    emit(col, rows, r, per_row=False)
                else:
                    if r is None or len(r) != len(rows):
                        raise JobException(
                            f"{n.name}: batch kernel returned "
                            f"{0 if r is None else len(r)} results "
                            f"for {len(rows)} inputs")
                    emit(col, rows, list(r), per_row=True)

        null_out_rows: List[int] = []

        def null_rows(rows: np.ndarray) -> None:
            keep = np.isin(rows, valid_out)
            if keep.any():
                null_out_rows.extend(rows[keep].tolist())

        def call_args_for(sel: np.ndarray) -> List[Any]:
            """Kernel arguments for compute positions `sel` (indices into
            the compute/col_pos arrays): per input column either a
            (k, ...) batch slice, a (k, W, ...) stencil gather, or per-row
            python objects."""
            args = []
            for b, pos in zip(in_batches, col_pos):
                p = pos[sel]           # (k, W)
                if is_array_data(b.data):
                    if has_stencil:
                        args.append(b.data[p.reshape(-1)].reshape(
                            p.shape + tuple(b.data.shape[1:])))
                    else:
                        q = p[:, 0]
                        if len(q) and np.array_equal(
                                q, np.arange(q[0], q[0] + len(q))):
                            args.append(b.data[q[0]:q[0] + len(q)])
                        else:
                            args.append(b.data[q])
                else:
                    if has_stencil:
                        args.append([[b.data[int(j)] for j in row]
                                     for row in p])
                    else:
                        args.append([b.data[int(j)] for j in p[:, 0]])
            return args

        ki.ensure_warm()
        # roofline attribution (util/coststats.py): device-kernel calls
        # join their analytical cost descriptor with measured seconds;
        # accumulated per op run so ONE op.efficiency event lands on the
        # op's trace span (per-chunk detail goes to the gauges)
        track_cost = _cs.enabled() and batched_call \
            and n.effective_device() == DeviceType.TPU
        run_secs = run_flops = run_bytes = 0.0
        t0 = time.time()
        try:
            with self.profiler.span("evaluate:" + n.name,
                                    rows=len(compute)):
                for lo, hi in run_bounds:
                    ki.maybe_reset(int(compute[lo]))
                    ki._last_row = int(compute[hi - 1])
                    i = lo
                    while i < hi:
                        j = min(i + batch, hi)
                        sel = np.arange(i, j)
                        dead = sel[null_in[sel]]
                        if len(dead):
                            null_rows(compute[dead])
                        if mask_nulls and len(dead) < len(sel):
                            # full-chunk call; dead rows' outputs are
                            # overwritten with nulls at assembly time
                            live = sel
                        else:
                            live = sel[~null_in[sel]]
                        if not len(live):
                            i = j
                            continue
                        if batched_call:
                            exec_sel, pad = live, 0
                            if use_buckets:
                                pad = bucket_for(len(live),
                                                 ladder) - len(live)
                                if pad:
                                    exec_sel = np.concatenate(
                                        [live,
                                         np.repeat(live[-1:], pad)])
                                    _M_OP_PAD_ROWS.labels(
                                        op=n.name,
                                        device=ki.dev_label).inc(pad)
                            args = call_args_for(exec_sel)
                            # a never-seen arg (device, shape, dtype)
                            # signature means XLA compiles a fresh
                            # executable for a jitted kernel — surface it
                            # live.  The device is part of the key: each
                            # assigned chip compiles its own ladder, and
                            # the CI ladder-bound guard holds per chip.
                            sig = (ki.dev_label,) + tuple(
                                (tuple(a.shape), str(a.dtype))
                                if is_array_data(a) else len(a)
                                for a in args)
                            new_sig = sig not in ki._shape_sigs
                            if new_sig:
                                ki._shape_sigs.add(sig)
                                _M_OP_RECOMPILES.labels(
                                    op=n.name,
                                    device=ki.dev_label).inc()
                                # a recompile inside a traced task is a
                                # latency cliff worth pinning to the
                                # exact op span that paid it
                                _tracing.add_event(
                                    "xla.recompile", op=n.name,
                                    device=ki.dev_label)
                            t_call = time.time()
                            if new_sig and track_cost:
                                # first call of a fresh signature: any
                                # XLA compile inside lands in the
                                # compile ledger under this (op,
                                # device, bucket)
                                with ki._call_lock, _cs.observe_compiles(
                                        n.name, ki.dev_label,
                                        len(exec_sel), repr(sig[1:])):
                                    res = ki.kernel.execute(*args)
                                # drain this unmeasured call's queued
                                # device work so the NEXT (measured)
                                # call times only itself
                                res = _cs.block_until_ready(res)
                            else:
                                with ki._call_lock:
                                    res = ki.kernel.execute(*args)
                            if track_cost and not new_sig:
                                # measured call seconds joined with the
                                # analytical descriptor; first calls of
                                # a signature are excluded so compile
                                # time never reads as inefficiency.
                                # Block on the result first: async
                                # dispatch would otherwise time the
                                # enqueue, not the op
                                res = _cs.block_until_ready(res)
                                call_s = time.time() - t_call
                                desc = _cs.descriptor_for(
                                    ki.kernel, n.name, ki.dev_label,
                                    len(exec_sel), args)
                                _cs.record_op_call(
                                    n.name, ki.dev_label,
                                    len(exec_sel), len(live), call_s,
                                    desc)
                                if desc is not None:
                                    run_secs += call_s
                                    run_flops += desc.flops or 0.0
                                    run_bytes += desc.bytes_total
                            if pad:
                                res = _strip_pad(res, len(live),
                                                 len(out_cols))
                            emit_result(compute[live], res)
                        else:
                            args = call_args_for(live)
                            row_args = []
                            for a in args:
                                e = a[0]
                                if has_stencil and is_array_data(a):
                                    e = list(a[0])
                                row_args.append(e)
                            with ki._call_lock:
                                res = ki.kernel.execute(*row_args)
                            emit_result(compute[live], _single(res, n, out_cols))
                        i = j
                if run_secs > 0:
                    cls = _cs.classify(ki.dev_label, run_flops or None,
                                       run_bytes, run_secs)
                    if cls is not None:
                        # straggler attribution: the op span carries
                        # its own roofline verdict, so a slow
                        # evaluate:<op> stage reads as INEFFICIENT
                        # (low eff) vs OVERLOADED (high eff, deep
                        # queues) in the master's analytics
                        _tracing.add_event(
                            "op.efficiency", op=n.name,
                            device=ki.dev_label,
                            eff=round(cls["eff"], 6),
                            bound=cls["bound"])
        except BaseException as e:
            # the kernel died mid-run: its internal state is partial and
            # _last_row may already claim the run's end.  Reset both so a
            # subsequent carry plan MISSES (fallback) instead of silently
            # continuing from half-advanced state, and a self-contained
            # re-run starts from a clean reset.
            if ki.spec.is_stateful:
                try:
                    ki.kernel.reset()
                finally:
                    ki._last_row = None
            if _ms.is_oom(e):
                # dispatch-site OOM forensics: the report names the
                # ledger entries (and their tasks) that held HBM when
                # this op's allocation failed
                _ms.note_oom(e, site="dispatch",
                             detail=f"op {n.name} on {ki.dev_label}")
            raise
        _M_OP_ROWS.labels(op=n.name).inc(len(compute))
        _M_OP_SECONDS.labels(op=n.name).inc(time.time() - t0)

        # assemble output columns in row order; null-propagated rows (rare)
        # interleave with kernel results, so columns containing them fall
        # back to per-element assembly
        null_set = set(null_out_rows)
        outputs: Dict[str, ColumnBatch] = {}
        for col in out_cols:
            parts = out_parts[col]
            if not parts and not null_set:
                outputs[col] = ColumnBatch(np.zeros(0, np.int64), [])
                continue
            if null_set:
                by_row: Dict[int, Elem] = {}
                for p in parts:
                    for r, e in zip(p.rows.tolist(), p.elements()):
                        by_row[r] = e
                # nulls LAST so they win: bucketed dispatch runs dead
                # rows through the kernel (full-chunk shape) and their
                # outputs must be discarded here
                for r in null_set:
                    by_row[int(r)] = NullElement()
                rows_sorted = np.asarray(sorted(by_row), np.int64)
                outputs[col] = ColumnBatch.from_elements(
                    rows_sorted, [by_row[int(r)] for r in rows_sorted])
            else:
                parts.sort(
                    key=lambda p: int(p.rows[0]) if len(p.rows) else 0)
                outputs[col] = concat_batches(parts)
            got = set(outputs[col].rows.tolist())
            if got != valid_set:
                missing = sorted(valid_set - got)
                raise JobException(
                    f"{n.name}: missing output rows {missing[:5]}...")
        return outputs

    # -- fused chains ---------------------------------------------------

    def _run_fused(self, n: O.OpNode, jr: A.JobRows, plan: A.TaskPlan,
                   store) -> Dict[str, ColumnBatch]:
        """Dispatch one fused chain at its tail node `n`: gather the
        composed stencil window from the HEAD member's input column,
        run the single jitted chain program through the chain's bucket
        ladder, and emit only the tail's outputs — member intermediates
        never materialize.  Chain-level row semantics reproduce the
        staged path exactly: REPEAT_EDGE padding at every member level
        (compose_positions), null propagation over the composed window
        (a tail row is null iff ANY transitively-read input row is
        null), bucketed tail-chunk padding, nulls-last assembly."""
        chain = self.chains[n.id]
        fki = self.fused[n.id]
        ts = plan.streams[n.id]
        fki.bind_stream(plan.job_idx, plan.slice_group)

        head = chain.head
        in_col = head.input_columns()[0]
        in_b = store[(in_col.op.id, in_col.column)]
        g = plan.slice_group if self.info.slice_level[n.id] > 0 else 0
        max_in = jr.rows[in_col.op.id][g]

        # one chain-wide batch cap (see FusedKernelInstance.cap_for)
        wp = int(getattr(jr, "work_packet_size", 0) or 0)
        batch = fki.cap_for(wp)
        use_buckets = _bucketing_enabled()
        ladder = bucket_ladder(batch) if use_buckets else None

        # device staging: ONE host->device move for the head column —
        # the only HBM traffic the whole chain pays on the input side
        if _device_staging_enabled() and isinstance(in_b.data, np.ndarray) \
                and in_b.data.dtype != object:
            in_b = in_b.to_device(fki.device)
        if in_b.convert is not None:
            in_b = in_b.converted()
        store[(in_col.op.id, in_col.column)] = in_b

        compute = np.asarray(ts.compute_rows, np.int64)
        out_cols = [c for c, _ in n.spec.output_columns]
        valid_out = np.asarray(ts.valid_output_rows, np.int64)
        valid_set = set(valid_out.tolist())

        # composed window positions per tail compute row (REPEAT_EDGE
        # at every member level = the staged transitive dilation)
        width = fki.width
        win_rows = fki.compose_positions(compute, max_in).reshape(
            len(compute), width)
        col_pos = in_b.positions(win_rows.reshape(-1)).reshape(
            win_rows.shape)

        # null propagation across the whole chain in one step
        null_in = np.zeros(len(compute), bool)
        if in_b.nulls is not None:
            null_in |= in_b.nulls[col_pos].any(axis=1)
        mask_nulls = use_buckets and (in_b.nulls is None
                                      or is_array_data(in_b.data))

        out_parts: Dict[str, List[ColumnBatch]] = {c: [] for c in out_cols}

        def emit(col: str, rows: np.ndarray, data, per_row: bool) -> None:
            keep = np.isin(rows, valid_out)
            if not keep.any():
                return
            if per_row:
                kept = [d for d, k in zip(data, keep) if k]
                out_parts[col].append(
                    ColumnBatch.from_elements(rows[keep], kept))
            else:
                if keep.all():
                    out_parts[col].append(ColumnBatch(rows, data))
                else:
                    idx = np.flatnonzero(keep)
                    out_parts[col].append(
                        ColumnBatch(rows[keep], data[idx]))

        def emit_result(rows: np.ndarray, res) -> None:
            if len(out_cols) == 1:
                cols_res = (res,)
            elif isinstance(res, tuple) and len(res) == len(out_cols):
                cols_res = res
            elif (isinstance(res, list) and len(res) == len(rows)
                  and all(isinstance(r, tuple) and len(r) == len(out_cols)
                          for r in res)):
                cols_res = tuple(list(col) for col in zip(*res))
            else:
                raise JobException(
                    f"{fki.chain_id}: expected {len(out_cols)}-tuple "
                    f"output")
            for col, r in zip(out_cols, cols_res):
                if is_array_data(r) and len(r) == len(rows):
                    emit(col, rows, r, per_row=False)
                else:
                    if r is None or len(r) != len(rows):
                        raise JobException(
                            f"{fki.chain_id}: fused chain returned "
                            f"{0 if r is None else len(r)} results "
                            f"for {len(rows)} inputs")
                    emit(col, rows, list(r), per_row=True)

        null_out_rows: List[int] = []

        def null_rows(rows: np.ndarray) -> None:
            keep = np.isin(rows, valid_out)
            if keep.any():
                null_out_rows.extend(rows[keep].tolist())

        def call_data(sel: np.ndarray):
            """The head-input gather for compute positions `sel`: a
            (k * width, ...) array in composed-window order (the chain
            body re-folds the window axes member by member)."""
            p = col_pos[sel].reshape(-1)
            if is_array_data(in_b.data):
                return in_b.data[p]
            # object column: stack per-row host data into one array
            return np.stack([np.asarray(in_b.data[int(j)]) for j in p])

        fki.ensure_warm()
        # chains are always batched TPU dispatch by construction
        track_cost = _cs.enabled()
        run_secs = run_flops = run_bytes = 0.0
        t0 = time.time()
        try:
            with self.profiler.span("evaluate:" + fki.chain_id,
                                    rows=len(compute)):
                i = 0
                while i < len(compute):
                    j = min(i + batch, len(compute))
                    sel = np.arange(i, j)
                    dead = sel[null_in[sel]]
                    if len(dead):
                        null_rows(compute[dead])
                    if mask_nulls and len(dead) < len(sel):
                        live = sel
                    else:
                        live = sel[~null_in[sel]]
                    if not len(live):
                        i = j
                        continue
                    exec_sel, pad = live, 0
                    if use_buckets:
                        pad = bucket_for(len(live), ladder) - len(live)
                        if pad:
                            exec_sel = np.concatenate(
                                [live, np.repeat(live[-1:], pad)])
                            _M_OP_PAD_ROWS.labels(
                                op=fki.chain_id,
                                device=fki.dev_label).inc(pad)
                    arr = call_data(exec_sel)
                    sig = (fki.dev_label, tuple(arr.shape),
                           str(arr.dtype))
                    new_sig = sig not in fki._shape_sigs
                    if new_sig:
                        fki._shape_sigs.add(sig)
                        _M_OP_RECOMPILES.labels(
                            op=fki.chain_id,
                            device=fki.dev_label).inc()
                        _tracing.add_event("xla.recompile",
                                           op=fki.chain_id,
                                           device=fki.dev_label)
                    t_call = time.time()
                    if new_sig and track_cost:
                        # fresh signature: ONE ledger entry for the
                        # whole chain, members recorded for attribution
                        with fki._call_lock, _cs.observe_compiles(
                                fki.chain_id, fki.dev_label,
                                len(exec_sel), repr(sig[1:]),
                                members=fki.member_names):
                            res = fki.execute(arr)
                        res = _cs.block_until_ready(res)
                    else:
                        with fki._call_lock:
                            res = fki.execute(arr)
                    if track_cost and not new_sig:
                        res = _cs.block_until_ready(res)
                        call_s = time.time() - t_call
                        desc, saved = fki.cost_for(arr.shape, arr.dtype)
                        cls = _cs.record_op_call(
                            fki.chain_id, fki.dev_label,
                            len(exec_sel), len(live), call_s, desc)
                        if cls is not None:
                            _fusion.chain_metrics_for(
                                fki.chain_id, fki.dev_label,
                                len(exec_sel), cls, saved)
                        if desc is not None:
                            run_secs += call_s
                            run_flops += desc.flops or 0.0
                            run_bytes += desc.bytes_total
                    if pad:
                        res = _strip_pad(res, len(live), len(out_cols))
                    emit_result(compute[live], res)
                    i = j
                if run_secs > 0:
                    cls = _cs.classify(fki.dev_label, run_flops or None,
                                       run_bytes, run_secs)
                    if cls is not None:
                        # straggler attribution for the fused span;
                        # the chain attr lets timeline consumers group
                        # fusion events without parsing op labels
                        _tracing.add_event(
                            "op.efficiency", op=fki.chain_id,
                            chain=fki.chain_id,
                            device=fki.dev_label,
                            eff=round(cls["eff"], 6),
                            bound=cls["bound"])
        except BaseException as e:
            if _ms.is_oom(e):
                _ms.note_oom(e, site="dispatch",
                             detail=f"chain {fki.chain_id} on "
                                    f"{fki.dev_label}")
            raise
        _M_OP_ROWS.labels(op=fki.chain_id).inc(len(compute))
        _M_OP_SECONDS.labels(op=fki.chain_id).inc(time.time() - t0)

        # assembly: identical to _run_kernel (nulls LAST so they win)
        null_set = set(null_out_rows)
        outputs: Dict[str, ColumnBatch] = {}
        for col in out_cols:
            parts = out_parts[col]
            if not parts and not null_set:
                outputs[col] = ColumnBatch(np.zeros(0, np.int64), [])
                continue
            if null_set:
                by_row: Dict[int, Elem] = {}
                for p in parts:
                    for r, e in zip(p.rows.tolist(), p.elements()):
                        by_row[r] = e
                for r in null_set:
                    by_row[int(r)] = NullElement()
                rows_sorted = np.asarray(sorted(by_row), np.int64)
                outputs[col] = ColumnBatch.from_elements(
                    rows_sorted, [by_row[int(r)] for r in rows_sorted])
            else:
                parts.sort(
                    key=lambda p: int(p.rows[0]) if len(p.rows) else 0)
                outputs[col] = concat_batches(parts)
            got = set(outputs[col].rows.tolist())
            if got != valid_set:
                missing = sorted(valid_set - got)
                raise JobException(
                    f"{fki.chain_id}: missing output rows "
                    f"{missing[:5]}...")
        return outputs


def _single(res, n, out_cols):
    """Wrap a batch=1 result to per-row list form for emit_result."""
    if len(out_cols) == 1:
        return [res]
    if not isinstance(res, tuple) or len(res) != len(out_cols):
        raise JobException(
            f"{n.name}: expected {len(out_cols)}-tuple output")
    return tuple([v] for v in res)
