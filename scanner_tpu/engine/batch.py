"""Batched column data for task evaluation.

The reference keeps per-element buffers from a pooled block allocator and
re-packs them into batches at each kernel call
(scanner/util/memory.cpp:269 BlockAllocator,
scanner/engine/evaluate_worker.cpp:1040-1100 batching).  On TPU the natural
design is stronger: a task's column is ONE contiguous array the whole way —
decoded straight into a batch buffer, moved host->device once, sliced (not
copied) into kernel calls, chained op-to-op as device arrays, and fetched
back exactly once at the sink.

`ColumnBatch` is that representation.  `data` is one of
  - ``np.ndarray``  — host batch, axis 0 = rows (uniform frames/blobs)
  - ``jax.Array``   — device batch, axis 0 = rows
  - ``list``        — arbitrary python objects (ragged frames, tuples, ...)
plus a sorted ``rows`` vector naming the (stream-local or global) row ids
and an optional ``nulls`` mask.  Gathers/slices on array data are views or
device ops; nothing round-trips through per-row python objects unless a
per-row (batch=1, non-array) consumer asks for it.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from ..common import NullElement
from ..util import faults as _faults
from ..util import memstats as _ms
from ..util import metrics as _mx

Elem = Any

# host<->device traffic over the PCIe/tunnel link — the 92-830 MB/s
# variance PERF.md round 3 had to reconstruct from traces becomes a
# live pair of counters (rate = delta bytes / delta seconds).  h2d
# seconds cover the device_put call (dispatch + synchronous copy part;
# the async completion rides under later compute by design), d2h
# seconds are the full blocking fetch.
_M_H2D_BYTES = _mx.registry().counter(
    "scanner_tpu_h2d_bytes_total",
    "Bytes staged host->device via ColumnBatch.to_device.")
_M_H2D_SECONDS = _mx.registry().counter(
    "scanner_tpu_h2d_seconds_total",
    "Seconds spent in host->device staging calls (dispatch side).")
_M_D2H_BYTES = _mx.registry().counter(
    "scanner_tpu_d2h_bytes_total",
    "Bytes fetched device->host via ColumnBatch.to_host.")
_M_D2H_SECONDS = _mx.registry().counter(
    "scanner_tpu_d2h_seconds_total",
    "Seconds spent blocking on device->host fetches.")


def staged_device_put(host: "np.ndarray", device, kind: str,
                      fault_detail: str):
    """The ONE engine host->device staging contract: the
    memory.pressure fault site, RESOURCE_EXHAUSTED forensics
    (site=staging), the shared h2d byte/second meters, and an
    allocation-ledger registration under `kind`.  Used by to_device
    AND the frame cache's fresh-row staging (engine/framecache.py), so
    the chaos/forensics/metering behavior of the two paths can never
    drift — and a cache-on/off A/B of `scanner_tpu_h2d_bytes_total`
    bills the same meter on both sides."""
    import jax
    t0 = time.time()
    lbl = _ms.device_label(device)
    try:
        if _faults.ACTIVE:
            _faults.inject("memory.pressure", detail=fault_detail)
        data = jax.device_put(host, device)
    except Exception as e:
        if _ms.is_oom(e):
            _ms.note_oom(e, site="staging",
                         detail=f"h2d {host.nbytes} bytes -> {lbl}")
        raise
    _M_H2D_SECONDS.inc(time.time() - t0)
    _M_H2D_BYTES.inc(host.nbytes)
    _ms.track_array(data, kind,
                    device=lbl if device is not None else None)
    return data


def _is_jax(x) -> bool:
    # cheap structural check that avoids importing jax for pure-host runs
    return type(x).__module__.startswith("jax")


def is_array_data(data) -> bool:
    return isinstance(data, np.ndarray) or _is_jax(data)


class ColumnBatch:
    """One column of one task: row ids + batched data (+ null mask).

    ``convert`` marks data stored in a pre-conversion wire format:
    ``("yuv420", h, w)`` means rows are flat planar I420 frames staged at
    1.5 B/px; ``converted()`` turns them into (n, h, w, 3) RGB where the
    data lives (device op for jax arrays, numpy for host).  Row-axis
    transforms (take/relabel/concat) preserve the mark — builtin gathers
    never look inside a frame — and any per-row host materialization
    converts transparently so no consumer can observe raw YUV bytes.
    """

    __slots__ = ("rows", "data", "nulls", "convert", "_row_pos")

    def __init__(self, rows: np.ndarray, data,
                 nulls: Optional[np.ndarray] = None,
                 convert: Optional[tuple] = None):
        self.rows = np.asarray(rows, np.int64)
        self.data = data
        self.nulls = nulls if nulls is None or nulls.any() else None
        self.convert = convert
        self._row_pos = None
        if not is_array_data(data) and len(data) != len(self.rows):
            raise ValueError(
                f"ColumnBatch: {len(data)} elements for {len(self.rows)} rows")
        if len(self.rows) > 1 and (np.diff(self.rows) <= 0).any():
            raise ValueError("ColumnBatch rows must be strictly increasing")

    # -- construction ---------------------------------------------------

    @staticmethod
    def from_elements(rows: Sequence[int], elems: Sequence[Elem]
                      ) -> "ColumnBatch":
        """Build from per-row elements; packs uniform ndarrays into one
        host batch, otherwise stores the object list."""
        rows = np.asarray(list(rows), np.int64)
        elems = list(elems)
        nulls = np.array([isinstance(e, NullElement) or e is None
                          for e in elems], bool)
        if nulls.all():
            return ColumnBatch(rows, [NullElement()] * len(elems), nulls)
        live = [e for e, n in zip(elems, nulls) if not n]
        first = live[0]
        if (isinstance(first, np.ndarray)
                and all(isinstance(e, np.ndarray) and e.shape == first.shape
                        and e.dtype == first.dtype for e in live)):
            if not nulls.any() and len(live) == len(elems):
                return ColumnBatch(rows, np.stack(elems))
            batch = np.zeros((len(elems),) + first.shape, first.dtype)
            batch[~nulls] = np.stack(live)
            return ColumnBatch(rows, batch, nulls)
        return ColumnBatch(rows, elems, nulls if nulls.any() else None)

    # -- row lookup -----------------------------------------------------

    def positions(self, rows: np.ndarray) -> np.ndarray:
        """Positions of `rows` (must all be present) in this batch."""
        pos = np.searchsorted(self.rows, rows)
        if (pos >= len(self.rows)).any() or (self.rows[pos] != rows).any():
            missing = sorted(set(np.asarray(rows).tolist())
                             - set(self.rows.tolist()))
            raise KeyError(f"rows not in batch: {missing[:5]}...")
        return pos

    # -- transforms (device-aware; views/slices where possible) ---------

    def take(self, positions: np.ndarray,
             new_rows: np.ndarray) -> "ColumnBatch":
        """Gather positions (−1 ⇒ null row) and relabel to new_rows."""
        positions = np.asarray(positions, np.int64)
        new_rows = np.asarray(new_rows, np.int64)
        neg = positions < 0
        nulls = None
        if self.nulls is not None:
            nulls = np.where(neg, True, self.nulls[np.where(neg, 0,
                                                            positions)])
        elif neg.any():
            nulls = neg
        safe = np.where(neg, 0, positions)
        if isinstance(self.data, np.ndarray):
            # contiguous slice stays a view
            if (not neg.any() and len(safe)
                    and np.array_equal(safe,
                                       np.arange(safe[0],
                                                 safe[0] + len(safe)))):
                data = self.data[safe[0]:safe[0] + len(safe)]
            else:
                data = self.data[safe]
        elif _is_jax(self.data):
            data = self.data[safe]  # on-device gather
        else:
            data = [NullElement() if neg[i] else self.data[int(p)]
                    for i, p in enumerate(safe)]
        return ColumnBatch(new_rows, data, nulls, convert=self.convert)

    def _contig_slice(self, start_row: int, k: int,
                      new_rows: np.ndarray) -> Optional["ColumnBatch"]:
        """Slice rows [start_row, start_row+k) directly if they are all
        present and contiguous in this batch (two binary-searched
        endpoint checks instead of a full positions lookup; array data
        stays a view).  None = not contiguous here, use the slow path."""
        p0 = int(np.searchsorted(self.rows, start_row))
        if p0 + k > len(self.rows) or self.rows[p0] != start_row \
                or self.rows[p0 + k - 1] != start_row + k - 1:
            return None
        nulls = self.nulls[p0:p0 + k] if self.nulls is not None else None
        return ColumnBatch(new_rows, self.data[p0:p0 + k], nulls,
                           convert=self.convert)

    def take_rows(self, rows: np.ndarray,
                  new_rows: Optional[np.ndarray] = None) -> "ColumnBatch":
        rows = np.asarray(rows, np.int64)
        nr = rows if new_rows is None else new_rows
        # contiguous [start, end) fast path — the sink hot path fetches
        # exactly this shape once per task
        k = len(rows)
        if k and len(self.rows) and int(rows[-1]) - int(rows[0]) == k - 1 \
                and (k == 1 or bool((np.diff(rows) == 1).all())):
            out = self._contig_slice(int(rows[0]), k, nr)
            if out is not None:
                return out
        return self.take(self.positions(rows), nr)

    def take_range(self, start: int, end: int) -> "ColumnBatch":
        """take_rows for the contiguous row range [start, end) without
        the caller materializing an index or this batch running the full
        positions lookup (executor._sink_rows hot path)."""
        rows = np.arange(start, end, dtype=np.int64)
        if len(rows) and len(self.rows):
            out = self._contig_slice(int(start), len(rows), rows)
            if out is not None:
                return out
        return self.take(self.positions(rows), rows)

    def relabel(self, new_rows: np.ndarray) -> "ColumnBatch":
        """Same data, new row ids (slice/unslice row renumbering)."""
        return ColumnBatch(new_rows, self.data, self.nulls,
                           convert=self.convert)

    # -- device movement ------------------------------------------------

    def to_device(self, device=None) -> "ColumnBatch":
        """Host -> device, one async transfer for the whole batch.
        `device` targets a specific chip (evaluator affinity: instance
        *i* stages to chip *i*); None keeps jax's default placement.  A
        batch already on device is re-staged only when it sits on a
        DIFFERENT chip than the requested one — the copy the old
        implicit-default path used to trigger silently inside the jitted
        call now happens here, visibly, and only when asked for.
        A convert-marked batch ships its WIRE format (that is the point:
        1.5 B/px over the link, convert on device via converted())."""
        if isinstance(self.data, np.ndarray):
            # the full staging contract — fault site, OOM forensics,
            # h2d meters, ledger registration — lives in ONE place
            # shared with the frame cache's staging path
            data = staged_device_put(
                self.data, device, "staging",
                fault_detail=f"h2d:{_ms.device_label(device)}:"
                             f"{self.data.nbytes}")
            return ColumnBatch(self.rows, data,
                               self.nulls, convert=self.convert)
        if device is not None and _is_jax(self.data):
            cur = None
            devs = getattr(self.data, "devices", None)
            if callable(devs):
                try:
                    cur = set(devs())
                except Exception:  # noqa: BLE001 — version drift
                    cur = None
            if cur is not None and cur != {device}:
                import jax
                try:
                    data = jax.device_put(self.data, device)
                except Exception as e:
                    if _ms.is_oom(e):
                        _ms.note_oom(
                            e, site="staging",
                            detail=f"cross-chip re-stage -> "
                                   f"{_ms.device_label(device)}")
                    raise
                _ms.track_array(data, "staging",
                                device=_ms.device_label(device))
                return ColumnBatch(self.rows, data,
                                   self.nulls, convert=self.convert)
        return self

    def prefetch_host(self) -> "ColumnBatch":
        """Start this batch's device->host copy WITHOUT blocking (the
        async half of the sink fetch): called at eval-done so the ~180 ms
        d2h latency of task k rides under the evaluation of task k+1
        instead of serializing inside the saver (PERF.md §1/§6).  The
        later to_host() then finds the transfer done (or in flight) and
        returns quickly.  No-op for host data; best-effort on jax
        versions without copy_to_host_async."""
        if _is_jax(self.data):
            # the sink batch sits in device memory until the saver's
            # fetch: account it so pre-fetch HBM pressure has an owner
            _ms.track_array(self.data, "sink")
            fn = getattr(self.data, "copy_to_host_async", None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — purely an overlap hint
                    pass
        return self

    def to_host(self) -> "ColumnBatch":
        """Materialize device data on host (the single sink-side fetch)."""
        if _is_jax(self.data):
            t0 = time.time()
            data = np.asarray(self.data)
            _M_D2H_SECONDS.inc(time.time() - t0)
            _M_D2H_BYTES.inc(data.nbytes)
            return ColumnBatch(self.rows, data, self.nulls,
                               convert=self.convert)
        return self

    def converted(self) -> "ColumnBatch":
        """Resolve a pending wire-format conversion (no-op otherwise).
        jax data converts with the jit device op, host arrays with the
        bit-identical numpy flavor (kernels/color.py)."""
        if self.convert is None:
            return self
        kind, h, w = self.convert
        if kind != "yuv420":
            raise ValueError(f"unknown convert mark {self.convert!r}")
        from ..kernels.color import yuv420_to_rgb_device, yuv420_to_rgb_host
        if _is_jax(self.data):
            data = yuv420_to_rgb_device(self.data, h, w)
        elif isinstance(self.data, np.ndarray):
            data = yuv420_to_rgb_host(self.data, h, w)
        else:
            raise ValueError(
                "convert-marked batch holds non-array data")
        return ColumnBatch(self.rows, data, self.nulls)

    # -- per-row access (host materialization boundary) -----------------

    def __len__(self) -> int:
        return len(self.rows)

    def is_null_pos(self, pos: int) -> bool:
        return self.nulls is not None and bool(self.nulls[pos])

    def element_at(self, pos: int) -> Elem:
        """Element at position `pos` (a view for host arrays; a
        convert-marked batch yields the CONVERTED row — raw wire bytes
        are never observable per-row)."""
        if self.is_null_pos(pos):
            return NullElement()
        if self.convert is not None:
            from ..kernels.color import yuv420_to_rgb_host
            _kind, h, w = self.convert
            row = np.asarray(self.data[pos])
            return yuv420_to_rgb_host(row, h, w)
        if _is_jax(self.data):
            return np.asarray(self.data[pos])
        return self.data[pos]

    def elements(self) -> List[Elem]:
        """All elements as per-row host objects (sink/write boundary)."""
        host = self.to_host()
        return [host.element_at(i) for i in range(len(host))]

    def get_row(self, row: int) -> Elem:
        return self.element_at(int(self.positions(
            np.asarray([row], np.int64))[0]))


def concat_batches(parts: List[ColumnBatch]) -> ColumnBatch:
    """Concatenate row-disjoint batches (already in row order)."""
    if len(parts) == 1:
        return parts[0]
    converts = {p.convert for p in parts}
    if len(converts) > 1:
        # mixed wire formats (shouldn't happen within one column; be safe)
        parts = [p.converted() for p in parts]
        converts = {None}
    convert = next(iter(converts))
    rows = np.concatenate([p.rows for p in parts])
    nulls = None
    if any(p.nulls is not None for p in parts):
        nulls = np.concatenate(
            [p.nulls if p.nulls is not None else np.zeros(len(p), bool)
             for p in parts])
    datas = [p.data for p in parts]
    if all(isinstance(d, np.ndarray) for d in datas) and \
            len({(d.shape[1:], d.dtype) for d in datas}) == 1:
        return ColumnBatch(rows, np.concatenate(datas), nulls,
                           convert=convert)
    if all(_is_jax(d) for d in datas):
        import jax.numpy as jnp
        if len({(tuple(d.shape[1:]), d.dtype) for d in datas}) == 1:
            return ColumnBatch(rows, jnp.concatenate(datas), nulls,
                               convert=convert)
    # mixed / ragged: fall back to object list
    elems: List[Elem] = []
    for p in parts:
        elems.extend(p.elements())
    return ColumnBatch(rows, elems, nulls)
