"""Client: the user entry point.

Capability parity: reference scannerpy/client.py (Client:58, run:1282,
ingest_videos:965, new_table:418, table:500, summarize:548) — here the
single-node path runs in-process; engine/service.py provides the
master/worker cluster path behind the same API.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Union

from ..common import CacheMode, DeviceType, PerfParams, ScannerException
from ..graph import analysis as A
from ..graph import ops as O
from ..graph.streams_dsl import IOGenerator, StreamsGenerator, TaskPartitioner
from ..storage import Database, make_storage
from ..storage import metadata as md
from ..storage.streams import NamedStream, NamedVideoStream
from ..util.profiler import Profile, Profiler
from .executor import LocalExecutor


class Table:
    """Read handle on a stored table (reference table.py:11)."""

    def __init__(self, db: Database, name: str):
        self._db = db
        self._name = name

    def id(self) -> int:
        return self._db.table_descriptor(self._name).id

    def name(self) -> str:
        return self._name

    def num_rows(self) -> int:
        return self._db.table_descriptor(self._name).num_rows

    def column_names(self) -> List[str]:
        return self._db.table_descriptor(self._name).column_names()

    def column(self, name: str):
        desc = self._db.table_descriptor(self._name)
        if desc.column_type(name) == md.ColumnType.VIDEO:
            s = NamedVideoStream(self._db, self._name)
        else:
            s = NamedStream(self._db, self._name)
            if name != "output":
                # direct column access bypasses the default-column logic
                return _ColumnReader(self._db, self._name, name)
        return s

    def committed(self) -> bool:
        return self._db.table_is_committed(self._name)


class _ColumnReader:
    def __init__(self, db: Database, table: str, column: str):
        self._stream = NamedStream(db, table)
        self._column = column

    def load(self, rows: Optional[Sequence[int]] = None):
        yield from self._stream.load(rows=rows, column=self._column)


class Client:
    """Create one per database.

    sc = Client(db_path="/data/db")
    frames = sc.io.Input([NamedVideoStream(sc, "movie", path="m.mp4")])
    hist = sc.ops.Histogram(frame=frames)
    sc.run(sc.io.Output(hist, [NamedStream(sc, "hists")]), PerfParams.estimate())
    """

    def __init__(self, db_path: Optional[str] = None,
                 storage_type: Optional[str] = None,
                 master: Optional[str] = None,
                 workers: Optional[List[str]] = None,
                 num_load_workers: int = 2,
                 num_save_workers: int = 2,
                 # None = resolve at job launch: one device-affine
                 # instance per local chip on multi-device hosts
                 # (engine/evaluate.py default_pipeline_instances).  An
                 # explicit value — including 1 — always wins.
                 pipeline_instances: Optional[int] = None,
                 decoder_threads: int = 1,
                 config_path: Optional[str] = None,
                 storage_options: Optional[Dict[str, Any]] = None,
                 metrics_port: Optional[int] = None,
                 compilation_cache_dir: Optional[str] = None,
                 **kw):
        if config_path is not None:
            from ..config import Config
            cfg = Config(config_path)
            db_path = db_path or cfg.db_path
            # [trace] enabled: the deployment-wide tracing default; the
            # SCANNER_TPU_TRACING env var (read at import) is the
            # per-process override and wins when set.  Applied in both
            # directions so a later Client with an enabling config
            # isn't stuck with an earlier one's disable.
            if not os.environ.get("SCANNER_TPU_TRACING"):
                from ..util import tracing
                tracing.set_enabled(cfg.tracing_enabled)
            # [trace] clocksync_enabled / rebase_clocks: cross-host
            # clock-offset estimation + trace-assembly rebase defaults;
            # SCANNER_TPU_CLOCKSYNC (read at import) wins when set
            from ..util import clocksync as _clk_cfg
            if not os.environ.get("SCANNER_TPU_CLOCKSYNC"):
                _clk_cfg.set_enabled(cfg.clocksync_enabled)
            _clk_cfg.set_rebase_enabled(cfg.rebase_clocks)
            # [memory] section: accounting default + report size; the
            # SCANNER_TPU_MEMSTATS* env vars (read at import) win
            from ..util import memstats
            if not os.environ.get("SCANNER_TPU_MEMSTATS"):
                memstats.set_enabled(cfg.memstats_enabled)
            memstats.set_report_top_n(cfg.memstats_report_top_n)
            # [perf] frame_cache_*: the paged HBM frame cache's
            # deployment defaults; the SCANNER_TPU_FRAME_CACHE* env
            # vars (read at import) win when set
            from .framecache import (set_capacity_mb, set_enabled,
                                     set_page_frames)
            if not os.environ.get("SCANNER_TPU_FRAME_CACHE"):
                set_enabled(cfg.frame_cache_enabled)
            set_capacity_mb(cfg.frame_cache_mb)
            set_page_frames(cfg.frame_cache_page_frames)
            # [perf] fusion_*: whole-pipeline XLA fusion defaults; the
            # SCANNER_TPU_FUSION env var (read at import) wins when set
            from ..graph import fusion as _fusion_cfg
            if not os.environ.get("SCANNER_TPU_FUSION"):
                _fusion_cfg.set_enabled(cfg.fusion_enabled)
            _fusion_cfg.set_min_chain(cfg.fusion_min_chain)
            # [alerts] section: health/SLO engine default + user rules;
            # the SCANNER_TPU_HEALTH env var (read at import) wins
            from ..util import health as _health_cfg
            if not os.environ.get("SCANNER_TPU_HEALTH"):
                _health_cfg.set_enabled(cfg.alerts_enabled)
            # [robustness] section: the master's write-ahead bulk
            # journal defaults; SCANNER_TPU_JOURNAL* env vars (read at
            # import) win per process
            from . import journal as _journal_cfg
            if not os.environ.get("SCANNER_TPU_JOURNAL"):
                _journal_cfg.set_enabled(cfg.journal_enabled)
            if not os.environ.get("SCANNER_TPU_JOURNAL_ROTATE"):
                _journal_cfg.set_rotate_records(
                    cfg.journal_rotate_records)
            # [gang] section: gang-scheduled multi-host execution
            # defaults; the SCANNER_TPU_GANG* env vars (read at
            # import) win per process
            from . import gang as _gang_cfg
            if not os.environ.get("SCANNER_TPU_GANG"):
                _gang_cfg.set_enabled(cfg.gang_enabled)
            if not os.environ.get("SCANNER_TPU_GANG_INIT_TIMEOUT"):
                _gang_cfg.set_init_timeout_s(cfg.gang_init_timeout_s)
            if not os.environ.get("SCANNER_TPU_GANG_FORM_TIMEOUT"):
                _gang_cfg.set_form_timeout_s(cfg.gang_form_timeout_s)
            if not os.environ.get("SCANNER_TPU_GANG_SHARDED"):
                _gang_cfg.set_sharded(cfg.gang_sharded)
            if not os.environ.get("SCANNER_TPU_GANG_HALO"):
                _gang_cfg.set_halo(cfg.gang_halo_exchange)
            # [control] section: how many master shards the control
            # plane runs ([control] shards); the
            # SCANNER_TPU_CONTROL_SHARDS env var (read at import) wins
            from . import shardmap as _shardmap_cfg
            if not os.environ.get("SCANNER_TPU_CONTROL_SHARDS"):
                _shardmap_cfg.set_num_shards(cfg.control_shards)
            # [remediation] section: the alert->action controller's
            # deployment defaults; SCANNER_TPU_REMEDIATION (read at
            # import) is the per-process kill switch and wins
            from . import controller as _ctrl_cfg
            if not os.environ.get("SCANNER_TPU_REMEDIATION"):
                _ctrl_cfg.set_enabled(cfg.remediation_enabled)
            _ctrl_cfg.set_dry_run(cfg.remediation_dry_run)
            _ctrl_cfg.set_autoscale_bounds(
                *cfg.remediation_autoscale_bounds)
            # applied in both directions (like [trace]): a config with
            # rules="" CLEARS user rules an earlier config installed —
            # removed rules' states resolve instead of firing forever
            _health_cfg.configure(cfg.alert_rules)
            # explicit argument beats config beats default
            storage_type = storage_type or cfg.storage_type
            if master is None:
                master = cfg.master_address
            if metrics_port is None:
                metrics_port = cfg.metrics_port
            # config is the LAST fallback: an explicit arg or the
            # per-process env var must win over the config file
            if compilation_cache_dir is None \
                    and not os.environ.get("SCANNER_TPU_COMPILATION_CACHE"):
                compilation_cache_dir = cfg.compilation_cache_dir
            # [faults] plan arms the chaos-injection registry for this
            # process (env var SCANNER_TPU_FAULTS, read at import time,
            # wins — it is the per-process override)
            if cfg.faults_plan and not os.environ.get("SCANNER_TPU_FAULTS"):
                from ..util import faults
                faults.install(cfg.faults_plan)
        # persistent XLA executable cache (arg > SCANNER_TPU_COMPILATION_CACHE
        # env > [perf] compilation_cache_dir config; unset = no-op): in-process
        # jobs re-load jitted kernel executables across runs (PERF.md §5)
        from ..util.jaxenv import enable_compilation_cache
        enable_compilation_cache(compilation_cache_dir)
        storage_type = storage_type or "posix"
        if db_path is None and storage_type == "posix":
            db_path = os.path.expanduser("~/.scanner_tpu/db")
        self._db = Database(make_storage(storage_type, db_path=db_path,
                                         **(storage_options or {})))
        self._db.load_megafile()
        self._profiler = Profiler(node="client")
        self._job_profiles: Dict[int, List[Profiler]] = {}
        # job id -> {"trace_id", "bulk_id"} for Client.trace()
        self._job_traces: Dict[int, Dict[str, Any]] = {}
        self._next_job_id = 0
        self._master_address = master
        self._cluster = None
        if master is not None:
            try:
                from .service import ClusterClient
            except ImportError as e:
                raise ScannerException(
                    "cluster mode requires scanner_tpu.engine.service") \
                    from e
            self._cluster = ClusterClient(master, db=self._db, **kw)

        # live telemetry endpoint — strictly opt-in (Client(metrics_port=)
        # or the [network] metrics_port config knob); port 0 binds an
        # ephemeral port, see self._metrics_server.port
        self._metrics_server = None
        if metrics_port is not None:
            from ..util.metrics import MetricsServer
            from ..util import coststats as _coststats
            from ..util import health as _health_st
            from ..util import memstats as _memstats
            from . import controller as _ctrl_st
            from . import framecache as _framecache
            self._metrics_server = MetricsServer(
                port=metrics_port,
                statusz=lambda: {"role": "client",
                                 "master": self._master_address,
                                 "db": getattr(self._db.backend, "root",
                                               None),
                                 "health": _health_st.status_dict(),
                                 "memory": _memstats.status_dict(),
                                 "framecache":
                                     _framecache.status_dict(),
                                 "efficiency":
                                     _coststats.status_dict(),
                                 "remediation":
                                     _ctrl_st.status_dict()},
                healthz=lambda: {"role": "client"})

        self.ops = O.OpGenerator()
        self.streams = StreamsGenerator()
        self.io = IOGenerator(self)
        self.partitioner = TaskPartitioner()
        # None stays None here (the per-job resolution in run() reads
        # it); the long-lived executor itself just needs a concrete int
        self._pipeline_instances_arg = pipeline_instances
        self._executor = LocalExecutor(
            self._db, self._profiler,
            num_load_workers=num_load_workers,
            num_save_workers=num_save_workers,
            pipeline_instances=pipeline_instances or 1,
            decoder_threads=decoder_threads)
        # health/SLO engine (util/health.py): local-mode runs get the
        # same backpressure/latency judgment cluster nodes do; no-op
        # when SCANNER_TPU_HEALTH=0 / [alerts] enabled=false
        from ..util import health as _health
        _health.ensure_started()
        # remediation controller (engine/controller.py): local-mode
        # runs get the worker-local playbooks (frame-cache shrink,
        # ladder re-warm); no-op when SCANNER_TPU_REMEDIATION=0
        from . import controller as _ctrl
        _ctrl.ensure_started()

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        if self._cluster is not None:
            self._cluster.close()
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    # -- live telemetry -----------------------------------------------------

    def job_status(self, bulk_id: Optional[int] = None) -> Dict[str, Any]:
        """Cluster job status (GetJobStatus): live progress of the given
        (default: active) bulk, plus `num_workers` even when no bulk is
        active — lets tooling wait for worker registration.  Cluster
        mode only."""
        if self._cluster is None:
            raise ScannerException(
                "job_status requires cluster mode (Client(master=...))")
        return self._cluster.job_status(bulk_id)

    def metrics(self) -> Dict[str, Any]:
        """Live metrics snapshot.  Cluster mode: the master's aggregated
        cluster-wide view (master + every live worker, each sample
        node-labeled).  Local mode: this process's registry under
        node="client".  Render with
        scanner_tpu.util.metrics.render_prometheus, or read values
        directly (see docs/observability.md for the series catalog)."""
        if self._cluster is not None:
            return self._cluster.metrics()
        from ..util.metrics import merge_snapshots, registry
        return merge_snapshots({"client": registry().snapshot()})

    def health(self) -> Dict[str, Any]:
        """Cluster health roll-up (docs/observability.md §Health &
        SLOs).  Cluster mode: the master's GetHealth view — worst-of
        `ok|degraded|unhealthy` across master + every live worker,
        node-prefixed reason codes, and each node's firing alerts
        (`{"status", "reasons", "firing", "nodes"}`).  Local mode: this
        process's health engine in the same shape under
        nodes["client"]."""
        if self._cluster is not None:
            return self._cluster.health()
        from ..util import health as _health
        return _health.merge_status({"client": _health.status_dict()})

    def memory_report(self) -> Dict[str, Any]:
        """Memory forensics (docs/observability.md §Memory).  Cluster
        mode: the master's GetMemoryReport view — its live HBM/
        allocation-ledger snapshot plus every one-shot OOM report
        workers shipped (each naming the top ledger entries by bytes
        with their owning task and trace id).  Local mode: this
        process's memstats view and last OOM report, if any."""
        if self._cluster is not None:
            return self._cluster.memory_report()
        from ..util import memstats
        last = memstats.last_report()
        return {"memory": memstats.status_dict(),
                "reports": [last] if last else []}

    def compile_report(self) -> Dict[str, Any]:
        """Compute-efficiency report (docs/observability.md
        §Efficiency & Compilation).  Cluster mode: the master's
        GetCompileLedger view — per node, the bounded XLA compile
        ledger (op, device, bucket, compile seconds, persistent-cache
        hit|miss|uncached, executable size, analytical cost), its
        summary with the cache hit rate, and the per-(op, device,
        bucket) roofline table (achieved FLOP/s, achieved bytes/s,
        compute-vs-memory bound, EFF%).  Local mode: this process's
        view in the same shape under nodes["client"]."""
        if self._cluster is not None:
            return self._cluster.compile_report()
        from ..util import coststats
        return {"nodes": {"client": coststats.compile_report()}}

    def shutdown_cluster(self, workers: bool = True) -> int:
        """Remotely stop the cluster this client is attached to: the
        master forwards Shutdown to every registered worker (unless
        workers=False), then stops itself — blocking start_master /
        start_worker processes exit 0.  Returns the number of workers
        that acknowledged.  Cluster mode only."""
        if self._cluster is None:
            raise ScannerException(
                "shutdown_cluster requires cluster mode "
                "(Client(master=...))")
        return self._cluster.shutdown_cluster(workers=workers)

    # -- data management ----------------------------------------------------

    def ingest_videos(self, named_paths: Sequence, inplace: bool = False,
                      force: bool = False):
        """Ingest videos as tables; returns (descriptors, failures) where
        failures is [(path, reason)] — a corrupt file is reported, not
        raised, so it cannot abort a corpus ingest (reference
        client.py:965 / ingest.cpp:872 failed_videos)."""
        from ..video import ingest_videos
        return ingest_videos(self._db, named_paths, inplace=inplace,
                             force=force)

    def ingest_images(self, name: str, paths: Sequence[str]):
        from ..video.ingest import ingest_images
        return ingest_images(self._db, name, paths)

    def load_op(self, module_path: str, name: Optional[str] = None):
        """Load a user op library: a Python module whose import registers
        ops via @register_op (the TPU-native analogue of the reference's
        dynamic .so op libraries, client.py:514 load_op)."""
        import hashlib
        import importlib.util
        import sys
        real = os.path.realpath(module_path)
        # unique module name per file path: never collides with stdlib or
        # a second op library of the same basename
        digest = hashlib.md5(real.encode()).hexdigest()[:8]
        base = os.path.splitext(os.path.basename(real))[0]
        mod_name = name or f"scanner_tpu_userops_{base}_{digest}"
        if mod_name in sys.modules:
            return sys.modules[mod_name]
        spec = importlib.util.spec_from_file_location(mod_name, module_path)
        if spec is None or spec.loader is None:
            raise ScannerException(f"cannot load op module: {module_path}")
        mod = importlib.util.module_from_spec(spec)
        # register before exec so pickled objects from the module resolve
        # in other processes (workers load_op the same path)
        sys.modules[mod_name] = mod
        try:
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(mod_name, None)
            raise
        return mod

    def batch_load(self, streams: Sequence, rows=None, workers: int = 4):
        """Load several streams concurrently (reference Client.batch_load,
        client.py:1270 multiprocessing pool -> thread pool here: the
        decode path releases the GIL in C)."""
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=workers) as pool:
            return list(pool.map(
                lambda s: list(s.load(rows=rows)), streams))

    def new_table(self, name: str, columns: Sequence[str],
                  rows: Sequence[Sequence[bytes]],
                  overwrite: bool = False) -> Table:
        self._db.new_table(name, columns, rows, overwrite=overwrite)
        return Table(self._db, name)

    def table(self, name: str) -> Table:
        if not self._db.has_table(name):
            raise ScannerException(f"no such table: {name}")
        return Table(self._db, name)

    def has_table(self, name: str) -> bool:
        return self._db.has_table(name)

    def delete_table(self, name: str) -> None:
        self._db.delete_table(name)

    def summarize(self) -> str:
        lines = ["table                          rows  committed"]
        for name in self._db.list_tables():
            try:
                desc = self._db.table_descriptor(name)
                lines.append(f"{name:28} {desc.num_rows:6}  "
                             f"{self._db.table_is_committed(name)}")
            except Exception:
                lines.append(f"{name:28}      ?  ?")
        out = "\n".join(lines)
        print(out)
        return out

    # -- execution ----------------------------------------------------------

    def run(self, outputs: Union[O.OpNode, Sequence[O.OpNode]],
            perf_params: Optional[PerfParams] = None,
            cache_mode: CacheMode = CacheMode.Error,
            show_progress: bool = True,
            profiling: bool = True,
            task_timeout: float = 0.0,
            **kw) -> int:
        """Execute a job set; returns a job id usable with get_profile."""
        if isinstance(outputs, O.OpNode):
            outputs = [outputs]
        perf = perf_params or PerfParams.estimate()
        if task_timeout:
            perf.task_timeout = task_timeout
        job_id = self._next_job_id
        self._next_job_id += 1
        prof = Profiler(node=f"job{job_id}")
        if self._cluster is not None:
            # the job's root trace span: NewJob (and the status polls)
            # run under it, so the master admits the bulk with this
            # trace_id and every worker task span chains back here
            from ..util import tracing as _tr
            tracer = _tr.default_tracer()
            root = _tr.open_span(tracer, "job", mode="cluster")
            try:
                with _tr.use_span(tracer, root):
                    profs = self._cluster.run(outputs, perf, cache_mode,
                                              show_progress)
            finally:
                _tr.close_span(tracer, root)
                # contribute the root span so the master's assembled
                # trace is self-contained (scanner_trace --verify walks
                # every chain to the root without this process)
                if root is not None \
                        and self._cluster.last_bulk_id is not None:
                    self._cluster.ship_spans(
                        self._cluster.last_bulk_id,
                        tracer.spans_for_trace(root.trace_id))
            self._job_profiles[job_id] = profs
            self._job_traces[job_id] = {
                "trace_id": root.trace_id if root else None,
                "bulk_id": self._cluster.last_bulk_id}
            return job_id
        # gang mode needs a cluster to co-schedule across: a local
        # (in-process) run IS a single host, so gang_hosts degrades to
        # ordinary execution — the degenerate 1-host gang — instead of
        # erroring (the same graph runs either way)
        if int(getattr(perf, "gang_hosts", 0) or 0):
            import logging
            logging.getLogger("scanner_tpu.engine").info(
                "gang_hosts=%d requested on a local run: executing as "
                "a single-host job (gang scheduling needs "
                "Client(master=...))", perf.gang_hosts)
        # instance-count resolution: explicit kwarg > PerfParams >
        # explicit Client(pipeline_instances=) — any of which wins as
        # given, including 1 — and only a fully-unset count resolves to
        # one device-affine instance per local chip on multi-chip hosts
        # (engine/evaluate.py default_pipeline_instances)
        from .evaluate import default_pipeline_instances
        ex = LocalExecutor(
            self._db, prof,
            num_load_workers=self._executor.num_load_workers,
            num_save_workers=self._executor.num_save_workers,
            decoder_threads=self._executor.decoder_threads,
            pipeline_instances=kw.get(
                "pipeline_instances",
                default_pipeline_instances(
                    perf.pipeline_instances_per_node
                    or self._pipeline_instances_arg)))
        ex.run(outputs, perf, cache_mode=cache_mode,
               show_progress=show_progress)
        self._job_profiles[job_id] = [prof]
        self._job_traces[job_id] = {"trace_id": ex.last_trace_id,
                                    "bulk_id": None}
        return job_id

    def load_frames(self, table: str, rows, column: str = "frame"):
        """Decode exact frames of a stored video stream (public accessor
        for the client-side read path, reference storage.py load)."""
        from ..video import load_frames
        return load_frames(self._db, table, rows, column)

    def get_profile(self, job_id: int) -> Profile:
        if job_id not in self._job_profiles:
            raise ScannerException(f"no profile for job {job_id}")
        return Profile(self._job_profiles[job_id])

    def trace(self, job_id: int, path: Optional[str] = None,
              raw_clocks: bool = False) -> str:
        """Write ONE merged cross-host Perfetto/Chrome trace for a
        finished job: the assembled span tree (client root → master
        scheduling → worker task → stage → op, all under the job's
        trace_id) plus any captured XLA device timelines — cluster
        profiles carry their device events inline, so remote chips'
        lanes survive the hop (util/jaxprof.py).  Remote spans arrive
        rebased onto master time via the per-node clock offsets
        (docs/observability.md §Cross-host time) unless raw_clocks=True
        keeps each host's uncorrected stamps.  Returns the path
        written.  Open in ui.perfetto.dev; `tools/scanner_trace.py` is
        the CLI flavor and adds straggler analytics."""
        from ..util import tracing as _tr
        info = self._job_traces.get(job_id)
        if info is None or not info.get("trace_id"):
            raise ScannerException(
                f"no trace for job {job_id} (was tracing disabled? "
                "SCANNER_TPU_TRACING / [trace] enabled)")
        if self._cluster is not None and info.get("bulk_id") is not None:
            reply = self._cluster.get_trace(info["bulk_id"],
                                            raw_clocks=raw_clocks)
            # the run already shipped this process's root span; merge
            # the flight recorder anyway (dedup by span id) in case
            # that best-effort ship was lost
            by_id = {d["span_id"]: d for d in reply.get("spans") or []}
            for d in _tr.default_tracer().spans_for_trace(
                    info["trace_id"]):
                by_id.setdefault(d["span_id"], d)
            spans = list(by_id.values())
        else:
            spans = _tr.default_tracer().spans_for_trace(info["trace_id"])
            # local spans come from the bounded flight recorder: a big
            # job can evict its own early spans (incl. the root) — say
            # so instead of writing a silently partial trace
            if not any(d["name"] == "job" for d in spans):
                import logging
                logging.getLogger("scanner_tpu.tracing").warning(
                    "trace for job %d is partial: the flight recorder "
                    "(SCANNER_TPU_TRACE_RING) evicted its earliest "
                    "spans, including the root", job_id)
        from ..util.jaxprof import DEVICE_PID_BASE, load_device_events
        dev: List[Dict[str, Any]] = []
        base = DEVICE_PID_BASE
        for p in self._job_profiles.get(job_id, []):
            for rec in getattr(p, "device_traces", []):
                got = load_device_events(rec, pid_base=base)
                dev.extend(got)
                if got:
                    base += 1000
        path = path or f"scanner_trace_job{job_id}.json"
        return _tr.write_chrome_trace(spans, path, device_events=dev)

    def stragglers(self, job_id: int) -> Dict[str, Any]:
        """Straggler analytics for a job: per-stage span stats + the
        top-N slowest tasks with their trace ids.  Cluster mode reads
        the master's incrementally-maintained summary (also on
        GetJobStatus and /statusz); local mode computes it from this
        process's flight recorder."""
        from ..util import tracing as _tr
        info = self._job_traces.get(job_id)
        if info is None or not info.get("trace_id"):
            raise ScannerException(f"no trace for job {job_id}")
        if self._cluster is not None and info.get("bulk_id") is not None:
            reply = self._cluster.get_trace(info["bulk_id"])
            return reply.get("stragglers") or {}
        return _tr.straggler_summary(
            _tr.default_tracer().spans_for_trace(info["trace_id"]))
