"""Gang-scheduled multi-host execution: the member side.

The master (engine/service.py) forms a **gang** for each task of a
`gang_hosts=N` bulk: N live, non-preempting workers are co-scheduled,
minted a `(gang_id, gang_epoch)` fence, and handed rendezvous roles —
member 0's advertised gang address is the jax.distributed coordinator,
everyone gets `(process_id, num_processes)`.  This module is what a
worker does with its role:

  * **one process per gang epoch** — the member runs in a dedicated
    child process (`python -m scanner_tpu.engine.gang`), so joining the
    multi-process JAX runtime never collides with the worker's own
    (already-initialized) backend, a hung collective is bounded by the
    parent's member timeout instead of wedging the worker, and a
    re-formed gang at a new coordinator starts from a clean runtime
    (parallel/distributed.shutdown() covers the in-process case);
  * the child rendezvouses with a **bounded** `initialization_timeout`
    (`[gang] init_timeout_s`), then evaluates **mesh-partitioned**
    (`[gang] sharded`, the default): `shard_range` splits the task's
    output rows over the gang, each member loads/decodes ONLY its
    contiguous shard (the loader plan is restricted to `[lo, hi)`; the
    frame cache keys pages under the member's shard identity), stencil
    boundary rows move between neighbor members over the mesh
    (`parallel/halo.py` ppermute pair, `[gang] halo_exchange`) instead
    of widening each member's decode, its shard digest joins one jitted
    cross-host reduction over the gang mesh (`parallel/mesh.host_mesh`)
    — the collective both synchronizes the gang (a lost host bites
    HERE) and checks cross-host agreement — and the serialized output
    shards assemble over one all-gather
    (`parallel/distributed.all_gather_rows`): per-gang throughput is
    ~N× the replicated mode's.  `[gang] sharded=false` keeps the
    pre-sharding replicated evaluation (every member runs the whole
    task; only the digest is sharded);
  * **single-writer commit**: only member 0 saves sink output — on the
    sharded path after re-deriving the full-task rows from the gathered
    shards and verifying them against the collective total — and only
    after the agreement check passed; members 1..N-1 ack through the
    `GangMemberDone` RPC (extended to carry their shard digest for the
    master's shard commit fold), so sink writes are exactly-once per
    epoch;
  * the child dies with its parent (PR_SET_PDEATHSIG): killing a worker
    kills its gang runner mid-collective — the survivors' collectives
    fail or hang, their parents time the members out, and the master
    aborts + re-forms the gang at `epoch+1` on the remaining capacity
    (a smaller re-formed gang simply recomputes `shard_range` at its
    new `num_processes` — nothing about the sharded path is pinned to
    the original member count).

Failure classification: rendezvous/collective/timeout failures are
TRANSIENT (`GangFailed(transient=True)`) — the gang re-forms with zero
blacklist strikes on the survivors; an evaluate error inside the child
is classified like any worker task failure.

Kill switch: ``SCANNER_TPU_GANG=0`` / ``[gang] enabled`` makes workers
ignore gang mode (the master still forms gangs only for bulks that ask).
See docs/robustness.md §Gang scheduling.
"""

from __future__ import annotations

import functools as _functools
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from ..util import faults as _faults
from ..util import metrics as _mx
from ..util.log import get_logger

_log = get_logger("gang")

# the [gang] config keys this module accepts (scanner-check SC313 keeps
# config.default_config(), this tuple and the docs/guide.md rows in
# sync, all directions)
CONFIG_KEYS = ("enabled", "init_timeout_s", "form_timeout_s",
               "sharded", "halo_exchange")


def _flag(v: Optional[str], default: bool) -> bool:
    if v is None or v == "":
        return default
    return v.strip().lower() not in ("0", "false", "no", "off")


def _env_float(name: str, default: float, floor: float) -> float:
    """Env override with the same clamp the setter applies; a
    malformed value falls back to the default (WARNING) instead of
    taking the importing process down."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(floor, float(raw))
    except ValueError:
        _log.warning("ignoring malformed %s=%r (want seconds); using "
                     "%s", name, raw, default)
        return default


_enabled = _flag(os.environ.get("SCANNER_TPU_GANG"), True)
_init_timeout_s = _env_float("SCANNER_TPU_GANG_INIT_TIMEOUT", 60.0,
                             floor=1.0)
_form_timeout_s = _env_float("SCANNER_TPU_GANG_FORM_TIMEOUT", 5.0,
                             floor=0.05)
_sharded = _flag(os.environ.get("SCANNER_TPU_GANG_SHARDED"), True)
_halo_exchange = _flag(os.environ.get("SCANNER_TPU_GANG_HALO"), True)


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Deployment default ([gang] enabled); the SCANNER_TPU_GANG env
    var is read at import and wins."""
    global _enabled
    _enabled = bool(on)


def sharded_enabled() -> bool:
    return _sharded


def set_sharded(on: bool) -> None:
    """Deployment default ([gang] sharded): mesh-partitioned gang
    evaluation — each member computes only its row shard and the output
    assembles over the interconnect.  Off = the pre-sharding replicated
    evaluation (every member computes all rows; N× redundancy, 1×
    throughput).  The SCANNER_TPU_GANG_SHARDED env var is read at
    import and wins.  The MASTER's value decides per gang (the flag
    rides the role reply), so members can never disagree mid-gang."""
    global _sharded
    _sharded = bool(on)


def halo_enabled() -> bool:
    return _halo_exchange


def set_halo(on: bool) -> None:
    """Deployment default ([gang] halo_exchange): stencil boundary rows
    move between neighbor members over the mesh (parallel/halo.py
    ppermute pair) instead of each member widening its decode past the
    shard edge.  Off = members decode their halo rows locally (still
    sharded, still bit-exact — just redundant boundary decode).  The
    SCANNER_TPU_GANG_HALO env var is read at import and wins; like
    `sharded`, the master's value rides the role reply."""
    global _halo_exchange
    _halo_exchange = bool(on)


def init_timeout_s() -> float:
    return _init_timeout_s


def set_init_timeout_s(s: float) -> None:
    global _init_timeout_s
    _init_timeout_s = max(1.0, float(s))


def form_timeout_s() -> float:
    return _form_timeout_s


def set_form_timeout_s(s: float) -> None:
    global _form_timeout_s
    _form_timeout_s = max(0.05, float(s))


# gang lifecycle telemetry (docs/observability.md §Metric catalog):
# bumped by the master's formation/abort paths (engine/service.py
# imports these hooks), so the whole fleet's gang story reads off one
# scrape of the master
_M_FORMED = _mx.registry().counter(
    "scanner_tpu_gang_formed_total",
    "Gangs the master formed (a co-scheduled member set minted a fresh "
    "(gang_id, epoch) and handed rendezvous roles).")
_M_ABORTED = _mx.registry().counter(
    "scanner_tpu_gang_aborted_total",
    "Gangs aborted before completing, by reason (member_lost = "
    "stale/unregistered member, member_failed = a member reported "
    "rendezvous/collective/evaluate failure, preempted = a member "
    "advertised spot reclaim, timeout = the task timeout revoked the "
    "gang).  Each abort bumps the epoch and requeues the task for a "
    "fresh gang on the remaining capacity, strike-free.",
    labels=["reason"])
_M_REFORMS = _mx.registry().counter(
    "scanner_tpu_gang_reforms_total",
    "Gang formations for a task whose previous gang aborted — the "
    "loss-tolerant re-forming path (always at a higher epoch).")
_M_EPOCH = _mx.registry().gauge(
    "scanner_tpu_gang_epoch",
    "Highest gang epoch minted by this master for the active bulk; "
    "every gang RPC carries (gang_id, epoch) and stale-epoch replies "
    "are NACKed.")
_M_STALE_NACKS = _mx.registry().counter(
    "scanner_tpu_gang_stale_nacks_total",
    "Gang RPCs NACKed on (gang_id, epoch) fence grounds, by method — "
    "a completion/failure/ack from an aborted (or pre-failover) gang "
    "epoch that was refused instead of double-applied.",
    labels=["rpc"])


def count_formed(reform: bool) -> None:
    _M_FORMED.inc()
    if reform:
        _M_REFORMS.inc()


def count_aborted(reason: str) -> None:
    _M_ABORTED.labels(reason=reason).inc()


def set_epoch(epoch: int) -> None:
    _M_EPOCH.set(epoch)


def count_stale_nack(rpc: str) -> None:
    _M_STALE_NACKS.labels(rpc=rpc).inc()


# cross-host gang phase telemetry (docs/observability.md §Cross-host
# time; scanner-check SC314 keeps this tuple, the registrations below
# and the docs marker table in sync, all directions).  The member child
# times its phases and returns them in the result dict — its registry
# is never scraped — and the parent worker folds them here; the skew
# histogram observes on the MASTER, from offset-corrected member
# barrier arrivals.
GANG_PHASE_SERIES = (
    "scanner_tpu_gang_phase_seconds_total",
    "scanner_tpu_gang_barrier_skew_seconds",
)

_M_PHASE = _mx.registry().counter(
    "scanner_tpu_gang_phase_seconds_total",
    "Seconds gang members spent per phase (rendezvous = joining the "
    "multi-process runtime, stage = evaluating the task body, barrier "
    "= waiting for the slowest member at the pre-collective barrier, "
    "collective = the post-arrival cross-host reduction), by member "
    "role.  Folded from member-child results by the parent worker.",
    labels=["phase", "role"])
# skew is usually milliseconds; the default latency buckets start at
# 1ms but top out too coarse between 10-100ms, where gang health lives
_M_BARRIER_SKEW = _mx.registry().histogram(
    "scanner_tpu_gang_barrier_skew_seconds",
    "Per-(gang, epoch) barrier-arrival skew: max - min member arrival "
    "at the pre-collective barrier, computed on the master from "
    "offset-corrected member timestamps — the time every host donates "
    "to the slowest one.",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0))


def count_phases(phases: Optional[Dict[str, float]],
                 role: Optional[str]) -> None:
    """Fold one member child's phase seconds into this (parent worker)
    process's registry."""
    if not phases:
        return
    r = str(role or "member")
    for phase, s in phases.items():
        try:
            _M_PHASE.labels(phase=str(phase), role=r).inc(float(s))
        except (TypeError, ValueError):
            continue


def observe_barrier_skew(seconds: float) -> None:
    _M_BARRIER_SKEW.observe(max(float(seconds), 0.0))


# sharded-gang data-plane telemetry (docs/observability.md §Metric
# catalog; scanner-check SC315 keeps this tuple, the registrations
# below and the docs marker table in sync, all directions).  The first
# three fold member-child results in the parent worker — same path as
# the phase seconds; the commit-fold counter bumps on the MASTER, which
# cross-checks every member's reported shard digest against the gang's
# collective total at completion (the shard commit fold).
GANG_SHARD_SERIES = (
    "scanner_tpu_gang_shard_rows_total",
    "scanner_tpu_gang_shard_decode_rows_total",
    "scanner_tpu_gang_shard_halo_bytes_total",
    "scanner_tpu_gang_shard_commit_folds_total",
)

_M_SHARD_ROWS = _mx.registry().counter(
    "scanner_tpu_gang_shard_rows_total",
    "Output rows gang members evaluated as THEIR shard on the "
    "mesh-partitioned path (sharded gangs sum to the task's rows "
    "across members; replicated gangs never bump this).  Folded from "
    "member-child results by the parent worker, by member role.",
    labels=["role"])
_M_SHARD_DECODE_ROWS = _mx.registry().counter(
    "scanner_tpu_gang_shard_decode_rows_total",
    "Source rows each gang member's loader planned to read/decode for "
    "its shard (shard rows + any stencil halo it decoded locally) — "
    "the per-member decode-isolation signal: on an even N-host shard "
    "this is ~1/N of the replicated decode.  Folded from member-child "
    "results by the parent worker, by member role.",
    labels=["role"])
_M_SHARD_HALO_BYTES = _mx.registry().counter(
    "scanner_tpu_gang_shard_halo_bytes_total",
    "Bytes of stencil boundary rows a gang member received from its "
    "neighbors over the mesh halo exchange (parallel/halo.py) instead "
    "of decoding them locally.  Folded from member-child results by "
    "the parent worker, by member role.",
    labels=["role"])
_M_SHARD_FOLD = _mx.registry().counter(
    "scanner_tpu_gang_shard_commit_folds_total",
    "Master-side shard commit folds: at each sharded gang completion "
    "the master folds the members' reported per-shard digests and "
    "cross-checks their sum against the gang's collective total "
    "(ok = every member reported and the sums agree, mismatch = sums "
    "disagree — the completion is still member-0-verified, this flags "
    "a reporting-plane divergence, partial = a member's report never "
    "arrived before the gang retired).",
    labels=["result"])


def count_shard_stats(shard: Optional[Dict[str, Any]],
                      role: Optional[str]) -> None:
    """Fold one member child's sharded data-plane stats into this
    (parent worker) process's registry."""
    if not shard:
        return
    r = str(role or "member")
    try:
        _M_SHARD_ROWS.labels(role=r).inc(float(shard.get("rows") or 0))
        _M_SHARD_DECODE_ROWS.labels(role=r).inc(
            float(shard.get("decode_rows") or 0))
        _M_SHARD_HALO_BYTES.labels(role=r).inc(
            float(shard.get("halo_bytes") or 0))
    except (TypeError, ValueError):
        pass


def count_shard_fold(result: str) -> None:
    _M_SHARD_FOLD.labels(result=str(result)).inc()


# ---------------------------------------------------------------------------
# parent side: one member child per (gang, epoch)
# ---------------------------------------------------------------------------

def member_timeout_s(task_timeout: float) -> Optional[float]:
    """Wall-clock bound on one member child: rendezvous budget + work
    budget.  `task_timeout=0` means "no timeout" (PerfParams parity):
    the member gets NO deadline either — a runner blocked in a DEAD
    collective is still reaped promptly by the heartbeat gang-liveness
    callback (spawn_member `alive`), which is the mechanism that
    actually detects peer loss; a hard cap here would kill legitimate
    long tasks every attempt until the bulk blacklisted."""
    if not task_timeout or task_timeout <= 0:
        return None
    return init_timeout_s() + max(float(task_timeout), 30.0)


def spawn_member(request: Dict[str, Any],
                 timeout: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None,
                 alive=None) -> Dict[str, Any]:
    """Run one gang member to completion in a child process and return
    its result dict ({"ok": True, "digest": ...} or {"ok": False,
    "stage", "transient", "error"}).  Never raises: every failure shape
    — rendezvous, collective hang (timeout), child crash — comes back
    as a transient result the caller reports via GangFailed.

    `alive` (optional callback) is polled while waiting: returning
    False means the gang was aborted underneath this member (the
    master's heartbeat gang-liveness list) — the runner is reaped
    immediately instead of burning the member timeout blocked in a
    collective whose peer is gone.

    Chaos hooks fire HERE, in the worker process, so crash-mode plans
    model host death: the child carries PR_SET_PDEATHSIG and dies with
    us, mid-collective from its peers' point of view.  The child's env
    has SCANNER_TPU_FAULTS stripped — a fresh process per epoch would
    otherwise re-arm counted plans from zero every re-form and never
    converge."""
    detail = (f"gang={request.get('gang_id')}:"
              f"e{request.get('epoch')}:m{request.get('process_id')}")
    try:
        if _faults.ACTIVE:
            # rendezvous-time fault: raise = the member cannot join
            # (reported transient), crash = the host dies before its
            # runner even starts
            _faults.inject("gang.rendezvous", detail=detail)
    except Exception as e:  # noqa: BLE001 — injected rendezvous loss
        return {"ok": False, "stage": "rendezvous", "transient": True,
                "error": f"{type(e).__name__}: {e}"}
    import cloudpickle

    if timeout is None:
        timeout = member_timeout_s(request.get("task_timeout", 0))
    workdir = tempfile.mkdtemp(prefix="gang_member_")
    req_path = os.path.join(workdir, "request.bin")
    res_path = os.path.join(workdir, "result.bin")
    joined = res_path + ".joined"
    request = dict(request, joined_marker=joined)
    with open(req_path, "wb") as fh:
        fh.write(cloudpickle.dumps(request))
    child_env = dict(env if env is not None else os.environ)
    child_env.pop("SCANNER_TPU_FAULTS", None)
    # deliberate child-side plan pass-through: SCANNER_TPU_GANG_CHILD_FAULTS
    # arms the MEMBER process itself (e.g. a gang.collective delay that
    # must slow the member's barrier arrival, not the parent's poll
    # loop).  Kept separate from SCANNER_TPU_FAULTS so counted
    # crash-mode plans stay parent-side and converge across re-forms.
    child_plan = (env if env is not None else os.environ).get(
        "SCANNER_TPU_GANG_CHILD_FAULTS")
    if child_plan:
        child_env["SCANNER_TPU_FAULTS"] = child_plan
    proc = subprocess.Popen(
        [sys.executable, "-m", "scanner_tpu.engine.gang",
         req_path, res_path],
        env=child_env)
    deadline = time.time() + timeout if timeout else None
    injected_collective = False
    try:
        while proc.poll() is None:
            if not injected_collective and os.path.exists(joined):
                injected_collective = True
                try:
                    if _faults.ACTIVE:
                        # the child has rendezvoused and is entering
                        # the collective: a crash here kills worker AND
                        # runner (pdeathsig) — host loss mid-collective
                        _faults.inject("gang.collective", detail=detail)
                except Exception as e:  # noqa: BLE001
                    proc.kill()
                    proc.wait()
                    return {"ok": False, "stage": "collective",
                            "transient": True,
                            "error": f"{type(e).__name__}: {e}"}
            if alive is not None and not alive():
                _log.warning("gang member %s: gang aborted underneath "
                             "this member — reaping the runner",
                             detail)
                proc.kill()
                proc.wait()
                return {"ok": False, "stage": "aborted",
                        "transient": True,
                        "error": "gang aborted while member ran"}
            if deadline is not None and time.time() > deadline:
                _log.warning("gang member %s timed out after %.1fs: "
                             "killing the runner", detail, timeout)
                proc.kill()
                proc.wait()
                return {"ok": False, "stage": "timeout",
                        "transient": True,
                        "error": f"member timed out after {timeout:.1f}s "
                                 "(peer lost mid-collective?)"}
            time.sleep(0.05)
        if os.path.exists(res_path):
            with open(res_path, "rb") as fh:
                return cloudpickle.loads(fh.read())
        # no result file: the runner died hard (injected host loss, OOM
        # kill, a crashed peer's coordination-service shutdown) — the
        # same transient member-loss shape as a timeout
        return {"ok": False, "stage": "crash", "transient": True,
                "error": f"gang member runner exited "
                         f"{proc.returncode} with no result"}
    finally:
        for p in (req_path, res_path, joined):
            try:
                os.unlink(p)
            except OSError:
                pass
        try:
            os.rmdir(workdir)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# child side: the member body
# ---------------------------------------------------------------------------

def _die_with_parent() -> None:
    """PR_SET_PDEATHSIG(SIGKILL): the runner must not outlive its
    worker — an orphaned member completing (or committing) after its
    host 'died' would violate the gang's loss semantics.  Linux only;
    elsewhere the parent's kill-on-timeout is the backstop."""
    try:
        import ctypes
        import signal
        libc = ctypes.CDLL(None, use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:  # noqa: BLE001
        pass


def _digest_rows(rows) -> int:
    """Deterministic uint32 digest of one shard's result rows: bytes
    rows hash directly, array-likes via their buffer, null rows as a
    fixed sentinel — the cross-host agreement currency.  Object-dtype
    arrays and unhashable row types contribute a constant only (their
    buffer holds process-local pointers, which would make identical
    rows disagree across hosts; agreement then still covers row
    counts)."""
    import zlib

    import numpy as np

    from ..common import NullElement
    acc = 0
    for r in rows:
        if isinstance(r, (bytes, bytearray, memoryview)):
            acc = (acc + zlib.crc32(bytes(r))) & 0xFFFFFFFF
        elif isinstance(r, NullElement):
            acc = (acc + 0x9E3779B9) & 0xFFFFFFFF
        else:
            try:
                arr = np.asarray(r)
                if arr.dtype == object:
                    raise TypeError("object rows digest by count")
                acc = (acc + zlib.crc32(np.ascontiguousarray(arr)
                                        .tobytes())) & 0xFFFFFFFF
            except Exception:  # noqa: BLE001
                acc = (acc + 1) & 0xFFFFFFFF
    return acc


def shard_range(n_rows: int, process_id: int,
                num_processes: int) -> tuple:
    """Contiguous per-host row shard [lo, hi) of a task's rows — the
    one split BOTH planes key off: digest staging and, on the sharded
    path, the data rows each member loads/decodes/evaluates.  Ceil-chunk
    layout (equal chunks, remainder on the last non-empty shard, tail
    shards possibly empty) — parallel/distributed.shard_rows — so shard
    blocks stage through the uneven host_local_array path with zero
    re-indexing."""
    from ..parallel.distributed import shard_rows
    return shard_rows(n_rows, process_id, num_processes)


def _gang_mesh(num_processes: int):
    """The ("hosts", "local") mesh spanning the gang's global device
    set (parallel/mesh.host_mesh): row p = member p's local devices."""
    from ..parallel.mesh import host_mesh
    return host_mesh(num_processes)


def _collective_digest_sum(num_processes: int, process_id: int,
                           local_digest: int) -> int:
    """One jitted cross-host reduction over the global mesh: every
    member stages its shard digest as this host's block of a global
    array (parallel/distributed.host_local_array) and the sum comes
    back replicated — the gang's synchronization point AND its
    agreement signal.  Wraps mod 2**32 deterministically."""
    import jax
    import numpy as np

    from ..parallel.distributed import host_local_array

    mesh = _gang_mesh(num_processes)
    arr = host_local_array(
        mesh, ("hosts",),
        np.array([local_digest], dtype=np.uint32))
    total = _jit_sum_u32()(arr)
    return int(np.asarray(jax.device_get(total))) & 0xFFFFFFFF


@_functools.lru_cache(maxsize=1)
def _jit_sum_u32():
    # one jitted reduction for the process's lifetime — a fresh
    # jax.jit(lambda ...) per barrier would re-trace every epoch
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda a: jnp.sum(a, dtype=jnp.uint32))


def _all_gather_bytes(num_processes: int, payload: bytes) -> List[bytes]:
    """All-gather one variable-length byte payload per member over the
    gang mesh: a size round (so every member pads to the same width —
    collectives need static shapes), then one row-sharded gather of the
    padded buffers (parallel/distributed.all_gather_rows).  Returns the
    per-member payloads in rank order, identical on every member — the
    transport sharded members assemble output shards through."""
    import numpy as np

    from ..parallel.distributed import all_gather_rows

    mesh = _gang_mesh(num_processes)
    sizes = all_gather_rows(
        mesh, "hosts", np.array([len(payload)], dtype=np.int64))
    width = max(int(sizes.max()), 1)
    buf = np.zeros((1, width), dtype=np.uint8)
    if payload:
        buf[0, :len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    full = all_gather_rows(mesh, "hosts", buf)
    return [full[p, :int(sizes[p])].tobytes()
            for p in range(num_processes)]


def run_member(req: Dict[str, Any]) -> Dict[str, Any]:
    """The member body (runs inside the child process): rendezvous →
    evaluate → collective agreement → (member 0) save.  Returns a
    result dict; never raises.

    Phase instrumentation (docs/observability.md §Cross-host time):
    each phase gets a first-class child span under the gang root —
    `gang.rendezvous`, `gang.stage`, `gang.barrier` (entry →
    all-arrived = time donated to the slowest member) and
    `gang.collective` (all-arrived → result-ready) — and its wall
    seconds come back in the result dict ("phases"/"role") so the
    parent worker can fold them into the scraped registry."""
    from ..parallel.distributed import (CoordinatorConfig,
                                        RendezvousError, initialize,
                                        shutdown)
    from ..util import tracing as _tr
    pid = int(req["process_id"])
    num = int(req["num_processes"])
    tracer = _tr.Tracer(
        node=req.get("node") or f"gang-m{pid}", export=True)
    ctx = _tr.parse_traceparent(req.get("traceparent"))
    attrs = {"gang": req.get("gang_id"), "epoch": req.get("epoch"),
             "member": pid, "num": num,
             "job": req.get("job_idx"), "task": req.get("task_idx")}
    phases: Dict[str, float] = {}
    role = "coordinator" if pid == 0 else "member"
    t_rz = time.time()
    rz = _tr.open_span(tracer, "gang.rendezvous", parent=ctx, **attrs)
    try:
        # current-span context so distributed.initialize's rendezvous
        # events land ON the gang.rendezvous span's timeline
        with _tr.use_span(tracer, rz):
            initialize(
                CoordinatorConfig(address=req["coordinator"],
                                  num_processes=num, process_id=pid),
                init_timeout=float(req.get("init_timeout")
                                   or init_timeout_s()))
    except RendezvousError as e:
        _tr.close_span(tracer, rz, status="error")
        return {"ok": False, "stage": "rendezvous", "transient": True,
                "error": str(e), "spans": tracer.drain_export(),
                "phases": phases, "role": role}
    except Exception as e:  # noqa: BLE001
        _tr.close_span(tracer, rz, status="error")
        return {"ok": False, "stage": "rendezvous", "transient": True,
                "error": f"{type(e).__name__}: {e}",
                "spans": tracer.drain_export(),
                "phases": phases, "role": role}
    _tr.close_span(tracer, rz)
    phases["rendezvous"] = time.time() - t_rz
    marker = req.get("joined_marker")
    if marker:
        try:
            with open(marker, "w") as fh:
                fh.write("joined")
        except OSError:
            pass
    try:
        res = _member_body(req, pid, num, tracer, ctx, attrs, phases)
    except Exception as e:  # noqa: BLE001 — collective/commit errors
        # surface as a transient member failure, not a child crash
        res = {"ok": False, "stage": "collective", "transient": True,
               "error": f"{type(e).__name__}: {e}",
               "spans": tracer.drain_export()}
    finally:
        shutdown()
    res.setdefault("phases", phases)
    res.setdefault("role", role)
    return res


def _member_body(req: Dict[str, Any], pid: int, num: int,
                 tracer, ctx, attrs: Dict[str, Any],
                 phases: Dict[str, float]) -> Dict[str, Any]:
    import cloudpickle

    from ..storage import Database, make_storage
    from ..util import tracing as _tr
    from .executor import LocalExecutor, TaskItem

    db = Database(make_storage(req.get("storage_type") or "posix",
                               db_path=req["db_path"]))
    db.refresh_meta()
    ex = LocalExecutor(db)
    ex.tracer = tracer
    ex._stream_opt = False  # whole-task evaluation inside the member
    spec = cloudpickle.loads(req["spec"])
    info, jobs = ex.prepare_readonly(spec["outputs"], spec["perf"])
    job = jobs[int(req["job_idx"])]
    task_idx = int(req["task_idx"])
    if req.get("sharded") and num > 1:
        return _sharded_body(req, pid, num, tracer, ctx, attrs, phases,
                             ex, info, job, task_idx)
    w = TaskItem(job, task_idx, tuple(job.tasks[task_idx]),
                 attempt=int(req.get("attempt") or 0))
    w.trace_ctx = ctx
    t_stage = time.time()
    st = _tr.open_span(tracer, "gang.stage", parent=ctx, **attrs)
    try:
        ex.run_single_task(info, w, save=False,
                           span_attrs={"gang": req.get("gang_id"),
                                       "epoch": req.get("epoch"),
                                       "member": pid})
    except Exception as e:  # noqa: BLE001
        from .service import _is_transient_failure
        _tr.close_span(tracer, st, status="error")
        return {"ok": False, "stage": "evaluate",
                "transient": _is_transient_failure(e),
                "error": f"{type(e).__name__}: {e}",
                "spans": tracer.drain_export()}
    _tr.close_span(tracer, st)
    phases["stage"] = time.time() - t_stage
    # per-host digest shards: member p digests only rows [lo, hi) of
    # every sink's output, the collective assembles the full-task sum
    # across hosts, and member 0 — which evaluated the whole task —
    # cross-checks the assembled sum against its own local shard sums:
    # one diverging member fails the gang instead of committing
    start, end = w.output_range
    n_rows = end - start
    lo, hi = shard_range(n_rows, pid, num)
    sink_rows: List[Any] = []
    for sink in info.sinks:
        if sink.id in w.results:
            sink_rows.append(ex._sink_rows(w.results[sink.id],
                                           start, end))
    local = sum(_digest_rows(rows[lo:hi])
                for rows in sink_rows) & 0xFFFFFFFF
    total = _barrier_and_digest(req, pid, num, tracer, ctx, attrs,
                                phases, local)
    if pid == 0:
        expect = 0
        for p in range(num):
            plo, phi = shard_range(n_rows, p, num)
            expect = (expect + sum(_digest_rows(rows[plo:phi])
                                   for rows in sink_rows)) & 0xFFFFFFFF
        if total != expect:
            return {"ok": False, "stage": "agree", "transient": True,
                    "error": f"cross-host digest mismatch: collective "
                             f"sum {total} != member-0 expectation "
                             f"{expect}",
                    "spans": tracer.drain_export()}
        # agreement holds: the single writer commits, exactly once
        ex.save_results(info, w)
    else:
        ex._task_trace_end(w)
    return {"ok": True, "digest": total, "rows": n_rows,
            "shard_digest": local,
            "spans": tracer.drain_export()}


def _barrier_and_digest(req: Dict[str, Any], pid: int, num: int,
                        tracer, ctx, attrs: Dict[str, Any],
                        phases: Dict[str, float], local: int) -> int:
    """The gang's synchronization pair, shared by both evaluation modes:
    the zero-digest barrier reduction (time spent = time donated to the
    slowest member), then the real digest reduction (pure collective
    cost).  The child-side collective fault fires BEFORE barrier entry,
    so a delayed member arrives late and the skew/attribution planes
    see a real straggler, not a slowed parent poll."""
    from ..util import tracing as _tr
    if _faults.ACTIVE:
        _faults.inject("gang.collective",
                       detail=f"gang={req.get('gang_id')}:"
                              f"e{req.get('epoch')}:m{pid}")
    # barrier wait vs transfer/compute, split explicitly: the
    # entry/all-arrived events carry the timestamps the master's skew
    # fold compares.
    t_bar = time.time()
    bar = _tr.open_span(tracer, "gang.barrier", parent=ctx, **attrs)
    if bar is not None:
        bar.add_event("barrier.enter", member=pid)
    _collective_digest_sum(num, pid, 0)
    if bar is not None:
        bar.add_event("barrier.all_arrived", member=pid)
    _tr.close_span(tracer, bar)
    t_col = time.time()
    phases["barrier"] = t_col - t_bar
    col = _tr.open_span(tracer, "gang.collective", parent=ctx, **attrs)
    total = _collective_digest_sum(num, pid, local)
    _tr.close_span(tracer, col)
    phases["collective"] = time.time() - t_col
    return total


def _sharded_body(req: Dict[str, Any], pid: int, num: int,
                  tracer, ctx, attrs: Dict[str, Any],
                  phases: Dict[str, float], ex, info, job,
                  task_idx: int) -> Dict[str, Any]:
    """The mesh-partitioned member body: evaluate ONLY this member's
    row shard (the loader and frame cache see only [lo, hi) plus any
    locally-decoded stencil reach), agree through the same digest
    collective as the replicated path, all-gather the serialized output
    shards over the mesh, and let member 0 — the single writer —
    assemble and commit the full task after cross-checking the
    assembled rows against the collective total.  Per-gang throughput
    is ~N× the replicated path's; the failure machinery (epoch bump →
    re-form smaller, which simply recomputes shard_range at the new
    num_processes) carries over unchanged."""
    import cloudpickle
    import numpy as np

    from ..util import tracing as _tr
    from . import framecache as _fc
    from .batch import ColumnBatch
    from .executor import TaskItem

    start, end = (int(job.tasks[task_idx][0]),
                  int(job.tasks[task_idx][1]))
    n_rows = end - start
    lo, hi = shard_range(n_rows, pid, num)
    # mesh-aware frame cache: pages this member stages are keyed under
    # its (host-shard, device) identity — residency is 1/N per member
    # by construction (the shard plan only ever touches shard rows)
    _fc.set_host_shard(f"s{pid}of{num}")
    w = TaskItem(job, task_idx, (start + lo, start + hi),
                 attempt=int(req.get("attempt") or 0))
    w.trace_ctx = ctx
    halo_stats = {"bytes": 0}
    halo_plan = None
    if req.get("halo", True) and hi > lo and n_rows % num == 0:
        try:
            halo_plan = _plan_halo(info, job, task_idx, num, start, end)
        except Exception:  # noqa: BLE001 — planning is best-effort;
            halo_plan = None  # members fall back to local halo decode
    if halo_plan:
        w.halo_drop = {nid: hp["drops"][pid]
                       for nid, hp in halo_plan.items()}
        w.halo_fill = _make_halo_filler(pid, num, start, n_rows // num,
                                        halo_plan, halo_stats)
        # pre-warm the exchange on the REAL block geometry (frame shape
        # is in the shared job metadata, so every member derives the
        # same warm-up — SPMD-safe) so the one-time XLA trace/compile
        # and the mesh's first-collective setup land here, not inside
        # the timed stage phase the bench's rows/s is computed from
        from ..parallel.halo import warm_halo_exchange
        mesh = _gang_mesh(num)
        for nid in sorted(halo_plan):
            vm = (job.source_info.get(nid) or {}).get("video_meta")
            if vm is None or not (vm.height and vm.width):
                continue
            nl, nh = halo_plan[nid]["need"]
            warm_halo_exchange(
                mesh, (n_rows // num, int(vm.height), int(vm.width), 3),
                np.uint8, nl, nh)
    t_stage = time.time()
    st = _tr.open_span(tracer, "gang.stage", parent=ctx, **attrs)
    shard_rows_by_sink: Dict[int, List[Any]] = {}
    if hi > lo:
        try:
            ex.run_single_task(info, w, save=False,
                               span_attrs={"gang": req.get("gang_id"),
                                           "epoch": req.get("epoch"),
                                           "member": pid,
                                           "shard": f"{lo}:{hi}"})
        except Exception as e:  # noqa: BLE001
            from .service import _is_transient_failure
            _tr.close_span(tracer, st, status="error")
            return {"ok": False, "stage": "evaluate",
                    "transient": _is_transient_failure(e),
                    "error": f"{type(e).__name__}: {e}",
                    "spans": tracer.drain_export()}
        for sink in info.sinks:
            if w.results and sink.id in w.results:
                shard_rows_by_sink[sink.id] = ex._sink_rows(
                    w.results[sink.id], start + lo, start + hi)
    _tr.close_span(tracer, st)
    phases["stage"] = time.time() - t_stage
    local = sum(_digest_rows(rows)
                for rows in shard_rows_by_sink.values()) & 0xFFFFFFFF
    total = _barrier_and_digest(req, pid, num, tracer, ctx, attrs,
                                phases, local)
    # output assembly: one all-gather of the serialized shard rows over
    # the mesh — every member participates (the collective is SPMD),
    # member 0 consumes the result
    t_asm = time.time()
    asm = _tr.open_span(tracer, "gang.assemble", parent=ctx, **attrs)
    payload = cloudpickle.dumps(shard_rows_by_sink)
    blobs = _all_gather_bytes(num, payload)
    _tr.close_span(tracer, asm)
    phases["assemble"] = time.time() - t_asm
    shard_stats = {"lo": lo, "hi": hi, "rows": hi - lo,
                   "decode_rows": int(getattr(w, "decode_rows", 0)),
                   "halo_bytes": int(halo_stats["bytes"]),
                   "gather_bytes": sum(len(b) for b in blobs)}
    if pid != 0:
        ex._task_trace_end(w)
        return {"ok": True, "digest": total, "rows": n_rows,
                "shard_digest": local, "shard": shard_stats,
                "spans": tracer.drain_export()}
    # member 0: verify the ASSEMBLED rows against the collective total
    # — one agreement check covering both a diverging member and any
    # transport corruption in the gather — then commit, exactly once
    per_member = [cloudpickle.loads(b) for b in blobs]
    part_digests = [sum(_digest_rows(rows)
                        for rows in part.values()) & 0xFFFFFFFF
                    for part in per_member]
    expect = sum(part_digests) & 0xFFFFFFFF
    if total != expect:
        ex._task_trace_end(w, status="error")
        return {"ok": False, "stage": "agree", "transient": True,
                "error": f"cross-host digest mismatch: collective sum "
                         f"{total} != assembled-shard expectation "
                         f"{expect}",
                "spans": tracer.drain_export()}
    results: Dict[int, Any] = {}
    rows_global = np.arange(start, end, dtype=np.int64)
    for sink in info.sinks:
        full: List[Any] = []
        for part in per_member:
            full.extend(part.get(sink.id, ()))
        if len(full) != n_rows:
            ex._task_trace_end(w, status="error")
            return {"ok": False, "stage": "agree", "transient": True,
                    "error": f"sharded assembly produced {len(full)} "
                             f"rows for sink {sink.id}, task has "
                             f"{n_rows}",
                    "spans": tracer.drain_export()}
        results[sink.id] = ColumnBatch.from_elements(rows_global, full)
    ex._task_trace_end(w)
    wf = TaskItem(job, task_idx, (start, end), attempt=w.attempt)
    wf.results = results
    ex.save_results(info, wf)
    return {"ok": True, "digest": total, "rows": n_rows,
            "shard_digest": local, "shard": shard_stats,
            "shard_digests": part_digests,
            "spans": tracer.drain_export()}


def _plan_halo(info, job, task_idx: int, num: int, start: int,
               end: int) -> Dict[int, Dict[str, Any]]:
    """Decide — deterministically, from inputs every member shares —
    which video source nodes exchange their stencil boundary rows over
    the mesh instead of decoding them locally, and by how much.  Each
    member derives ALL members' shard plans (pure analysis, no IO), so
    the eligibility decision and the exchange extents are identical
    across the gang with no agreement round: either every member enters
    the node's halo collective, or none does.

    A node is eligible only when, for EVERY member: its own-window
    source rows are fully covered by its plan (so any neighbor's halo
    row has an owner that decoded it), its out-of-window in-task rows
    form a contiguous single-hop extension of the window (the ppermute
    pair reaches immediate neighbors only), and all in-task rows live
    in one table item (uniform frame geometry — exchange blocks must
    stack).  Rows outside the task range (stencil reach past the task
    edge) always decode locally and never enter the exchange."""
    import numpy as np

    from ..graph import analysis as A

    n_rows = end - start
    chunk = n_rows // num
    if chunk <= 0:
        return {}
    plans = [A.derive_task_streams(
        info, job.jr, (start + p * chunk, start + (p + 1) * chunk),
        job_idx=job.job_idx, task_idx=task_idx) for p in range(num)]
    out: Dict[int, Dict[str, Any]] = {}
    for nid, si in job.source_info.items():
        if "custom" in si or not si.get("is_video"):
            continue
        desc = si["table"]
        need_lo = need_hi = 0
        drops: List[Any] = []
        items = set()
        ok = False
        for p in range(num):
            plo = start + p * chunk
            phi = plo + chunk
            prows = np.asarray(plans[p].source_rows.get(nid, ()),
                               np.int64)
            pin = prows[(prows >= start) & (prows < end)]
            own = pin[(pin >= plo) & (pin < phi)]
            if len(own) != chunk or own[0] != plo \
                    or own[-1] != phi - 1:
                break
            drop = np.sort(pin[(pin < plo) | (pin >= phi)])
            if len(drop):
                nl = max(0, plo - int(drop.min()))
                nh = max(0, int(drop.max()) - phi + 1)
                if max(nl, nh) > chunk:
                    break
                want = np.concatenate([
                    np.arange(plo - nl, plo, dtype=np.int64),
                    np.arange(phi, phi + nh, dtype=np.int64)])
                if not np.array_equal(drop, want):
                    break
                need_lo = max(need_lo, nl)
                need_hi = max(need_hi, nh)
            items.update(desc.item_of_row(int(r)) for r in pin)
            drops.append(drop)
        else:
            ok = True
        if not ok or (need_lo == 0 and need_hi == 0) or len(items) != 1:
            continue
        out[nid] = {"need": (need_lo, need_hi), "drops": drops}
    return out


def _make_halo_filler(pid: int, num: int, start: int, chunk: int,
                      halo_plan: Dict[int, Dict[str, Any]],
                      halo_stats: Dict[str, int]):
    """Build the post-load hook (executor TaskItem.halo_fill) that runs
    the mesh halo exchange for every eligible node and splices the
    received neighbor rows into the loaded batch — replacing the local
    decode of those rows, which the loader skipped (TaskItem.halo_drop).
    Runs on EVERY member for EVERY eligible node (SPMD collectives);
    members that need no rows from a side still relay their edges."""

    def fill(info, w):
        import numpy as np

        from ..common import ScannerException
        from ..parallel.halo import exchange_row_halo
        from .batch import ColumnBatch

        mesh = _gang_mesh(num)
        plo = start + pid * chunk
        phi = plo + chunk
        for nid in sorted(halo_plan):
            hp = halo_plan[nid]
            need_lo, need_hi = hp["need"]
            batch = (w.elements or {}).get(nid)
            if batch is None:
                raise ScannerException(
                    f"halo fill: source node {nid} missing from the "
                    f"loaded elements")
            own = batch.take_range(plo, phi).to_host()
            block = own.data
            if not isinstance(block, np.ndarray) \
                    or block.dtype == object:
                raise ScannerException(
                    f"halo fill: node {nid} decoded to non-uniform "
                    f"data; geometry eligibility was violated")
            left, right = exchange_row_halo(mesh, block, need_lo,
                                            need_hi, "hosts")
            drop = np.asarray(hp["drops"][pid], np.int64)
            my_left = drop[drop < plo]
            my_right = drop[drop >= phi]
            add_rows: List[Any] = []
            add_data: List[Any] = []
            if len(my_left):
                take = left[len(left) - len(my_left):]
                add_rows.append(my_left)
                add_data.append(take)
                halo_stats["bytes"] += int(take.nbytes)
            if len(my_right):
                take = right[:len(my_right)]
                add_rows.append(my_right)
                add_data.append(take)
                halo_stats["bytes"] += int(take.nbytes)
            if not add_rows:
                continue
            host = batch.to_host()
            rows = np.concatenate([host.rows] + add_rows)
            order = np.argsort(rows, kind="stable")
            nulls = None
            if host.nulls is not None:
                nulls = np.concatenate(
                    [host.nulls,
                     np.zeros(sum(len(r) for r in add_rows), bool)]
                )[order]
            if isinstance(host.data, np.ndarray) \
                    and host.data.dtype != object:
                data = np.concatenate([host.data] + add_data)[order]
            else:
                elems = list(host.data)
                for blockx in add_data:
                    elems.extend(list(blockx))
                data = [elems[int(i)] for i in order]
            w.elements[nid] = ColumnBatch(rows[order], data, nulls,
                                          convert=host.convert)

    return fill


def main(argv: Optional[List[str]] = None) -> int:
    """Child entry: python -m scanner_tpu.engine.gang <req> <res>."""
    _die_with_parent()
    argv = argv if argv is not None else sys.argv[1:]
    req_path, res_path = argv[0], argv[1]
    import cloudpickle
    with open(req_path, "rb") as fh:
        req = cloudpickle.loads(fh.read())
    res = run_member(req)
    tmp = res_path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(cloudpickle.dumps(res))
    os.replace(tmp, res_path)
    return 0 if res.get("ok") else 3


if __name__ == "__main__":
    sys.exit(main())
