"""Minimal gRPC plumbing: named methods with msgpack-serialized dict
payloads.

Capability parity: the reference's control plane (scanner/engine/rpc.proto
service Master/Worker + grpc glue in util/grpc.h).  Instead of protoc
codegen, methods are registered dynamically on a generic handler — the
message schema lives in the handlers, serialization is msgpack (numpy-aware,
via storage.metadata pack/unpack).
"""

from __future__ import annotations

import random as _random
import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, Optional

import grpc

from ..common import ScannerException
from ..storage.metadata import pack, unpack
from ..util import faults as _faults
from ..util import metrics as _mx
from ..util import tracing as _tracing
from ..util.log import get_logger
from ..util.retry import call_with_backoff

_log = get_logger("rpc")

# per-process jitter factor for the channel reconnect pacing below: a
# fleet of workers that all lost the same master would otherwise share
# identical backoff caps and redial in lockstep — every survivor of a
# master restart hitting the fresh listener in the same 100 ms window.
# One multiplicative draw per process (seeded from the default RNG, so
# distinct across forks) decorrelates the fleet; call-level full-jitter
# backoff (util/retry.py) plus the process retry budget handle the rest.
_RECONNECT_JITTER = _random.uniform(0.7, 1.3)

GRPC_OPTIONS = [
    ("grpc.max_send_message_length", 1 << 30),
    ("grpc.max_receive_message_length", 1 << 30),
    # cap the CHANNEL-level reconnect backoff (gRPC default maxes at
    # 120s): a client whose peer is down for a while — a worker riding
    # out a master restart, wait_for_server polling a still-booting
    # server — would otherwise accumulate minutes of redial delay and
    # stay UNAVAILABLE long after the peer is actually back.  Our own
    # call-level full-jitter backoff handles politeness; the channel
    # just needs to redial promptly (with the per-process jitter above
    # so a whole fleet does not redial on one clock).
    ("grpc.initial_reconnect_backoff_ms", int(100 * _RECONNECT_JITTER)),
    ("grpc.min_reconnect_backoff_ms", int(100 * _RECONNECT_JITTER)),
    ("grpc.max_reconnect_backoff_ms", int(2000 * _RECONNECT_JITTER)),
]

# server-side handler latency (includes msgpack (de)serialization, not
# network time) — the live flavor of the profiler's RPC spans
_M_RPC_LATENCY = _mx.registry().histogram(
    "scanner_tpu_rpc_latency_seconds",
    "Server-side RPC handler latency by method (deserialize + handler + "
    "serialize).",
    labels=["method"])

# high-frequency poll/liveness methods: their traceparent still
# propagates (handlers can read the current context) but no server span
# is minted per call — a 4 Hz status poll over a long bulk would churn
# the flight recorder with nothing a timeline needs
_SPAN_SKIP = frozenset({"Ping", "Heartbeat", "GetJobStatus",
                        "GetMetrics", "PokeWatchdog"})


class RpcError(ScannerException):
    pass


class _GenericService(grpc.GenericRpcHandler):
    def __init__(self, service_name: str,
                 methods: Dict[str, Callable[[dict], dict]],
                 tracer: Optional[_tracing.Tracer] = None):
        self._prefix = f"/{service_name}/"
        self._methods = methods
        self._tracer = tracer

    def _handle(self, short_name: str, method, req: dict) -> dict:
        """Re-establish the caller's trace context around the handler:
        the `_traceparent` payload key (injected by RpcClient.call) is
        popped before the handler sees the request, and a server span
        `rpc:<Method>` is opened under it — the cross-host hop in the
        assembled task timeline."""
        ctx = _tracing.parse_traceparent(req.pop(
            _tracing.TRACEPARENT_KEY, None))
        tracer = self._tracer
        if ctx is None or tracer is None or not _tracing.enabled():
            return method(req)
        if short_name in _SPAN_SKIP:
            with _tracing.use_context(tracer, ctx):
                return method(req)
        with _tracing.start_span(tracer, f"rpc:{short_name}",
                                 parent=ctx):
            return method(req)

    def service(self, handler_call_details):
        name = handler_call_details.method
        if not name.startswith(self._prefix):
            return None
        method = self._methods.get(name[len(self._prefix):])
        if method is None:
            return None

        short_name = name[len(self._prefix):]

        def unary(request: bytes, context) -> bytes:
            t0 = time.time()
            try:
                if _faults.ACTIVE:
                    _faults.inject("rpc.server.handle", detail=short_name)
                return pack(self._handle(short_name, method,
                                         unpack(request)))
            except Exception as e:  # noqa: BLE001
                # the server-side stack would otherwise be discarded:
                # only "type: msg" crosses the wire in the INTERNAL
                # status, which is useless for debugging a handler bug
                _log.exception("RPC %s failed server-side", short_name)
                context.set_code(grpc.StatusCode.INTERNAL)
                context.set_details(f"{type(e).__name__}: {e}")
                return b""
            finally:
                _M_RPC_LATENCY.labels(method=short_name).observe(
                    time.time() - t0)

        return grpc.unary_unary_rpc_method_handler(unary)


class RpcServer:
    """One gRPC server hosting one named service."""

    def __init__(self, service_name: str,
                 methods: Dict[str, Callable[[dict], dict]],
                 port: int = 0, max_workers: int = 8,
                 tracer: Optional[_tracing.Tracer] = None):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers(
            (_GenericService(service_name, methods, tracer=tracer),))
        self.port = self._server.add_insecure_port(f"0.0.0.0:{port}")
        if self.port == 0:
            raise RpcError(f"could not bind port {port}")

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class RpcClient:
    """Stub for a remote service; call(method, **payload) -> dict.

    Transient transport failures (UNAVAILABLE — connection refused/reset,
    the server not yet listening) are retried with full-jitter exponential
    backoff, the analog of the reference's GRPC_BACKOFF wrapper
    (scanner/util/grpc.h, worker.cpp:886).  Only UNAVAILABLE is retried by
    default: the request provably never reached the server, so retrying
    cannot double-execute a non-idempotent method like NextWork.
    """

    def __init__(self, address: str, service_name: str,
                 timeout: float = 30.0, retries: int = 3,
                 backoff_base: float = 0.05, backoff_cap: float = 2.0):
        self.address = address
        self._service = service_name
        self._timeout = timeout
        self._retries = retries
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        self._channel = grpc.insecure_channel(address, options=GRPC_OPTIONS)

    @staticmethod
    def _transient(e: Exception) -> bool:
        return isinstance(e, grpc.RpcError) \
            and e.code() == grpc.StatusCode.UNAVAILABLE

    def call(self, method: str, timeout: Optional[float] = None,
             retries: Optional[int] = None, **payload) -> dict:
        fn = self._channel.unary_unary(
            f"/{self._service}/{method}",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x)
        # context propagation: the current span context (if any) rides
        # in the payload as `_traceparent`; the server glue pops it and
        # re-establishes the context around the handler, so one
        # trace_id follows a job across every hop with no handler
        # signature changing
        tp = _tracing.current_traceparent()
        if tp is not None:
            payload.setdefault(_tracing.TRACEPARENT_KEY, tp)
        req = pack(payload)
        # fault-plan selector detail: "<method>@<peer>" — method=/peer=
        # rule keys (and plain match= substrings) select per-RPC-method
        # and per-peer, so a plan can model an ASYMMETRIC partition
        # (this peer unreachable, others fine)
        detail = f"{method}@{self.address}"

        def attempt():
            # chaos hook fires per ATTEMPT (inside the backoff loop): an
            # injected UNAVAILABLE storm exercises the same retry path a
            # flapping network would
            if _faults.ACTIVE:
                _faults.inject("rpc.client.call", detail=detail)
            raw_reply = fn(req, timeout=timeout or self._timeout)
            if _faults.ACTIVE and _faults.take_duplicate(
                    "rpc.client.call", detail=detail):
                # duplicate delivery: the identical request hits the
                # server a second time and the first reply is dropped —
                # at-least-once semantics after an ambiguous timeout.
                # Only the server-side idempotency/dedup machinery
                # (RPC_CONTRACTS, NewJob admission tokens) may make
                # this safe; that is exactly what the drill verifies.
                raw_reply = fn(req, timeout=timeout or self._timeout)
            return raw_reply

        try:
            raw = call_with_backoff(
                attempt,
                is_transient=self._transient,
                retries=self._retries if retries is None else retries,
                base=self._backoff_base, cap=self._backoff_cap,
                # UNAVAILABLE retries become visible per method:
                # scanner_tpu_retry_attempts_total{site="rpc:NextWork"}
                label=f"rpc:{method}")
        except grpc.RpcError as e:
            raise RpcError(
                f"{self._service}.{method} @ {self.address}: "
                f"{e.code().name}: {e.details()}") from e
        return unpack(raw)

    def try_call(self, method: str, timeout: Optional[float] = None,
                 retries: Optional[int] = None, **payload) -> Optional[dict]:
        """call() that returns None on transport errors (for pings)."""
        try:
            return self.call(method, timeout=timeout, retries=retries,
                             **payload)
        except RpcError:
            return None
        except ValueError as e:
            # grpc raises ValueError ("Cannot invoke RPC on closed
            # channel!") after close() — treat a racing shutdown like any
            # other transport failure so ping/heartbeat threads die
            # quietly; any other ValueError is a real bug, let it surface
            if "closed channel" in str(e):
                return None
            raise

    def close(self) -> None:
        self._channel.close()


def wait_for_server(address: str, service: str, method: str = "Ping",
                    timeout: float = 10.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        # a FRESH channel per attempt: a channel first dialed while the
        # server was not yet listening can wedge in connection-refused
        # long after the server is up (observed under sandboxed network
        # stacks, where the reconnect path keeps failing while a new
        # channel connects instantly).  This loop is the retry policy,
        # so per-call retries stay off.
        c = RpcClient(address, service, timeout=2.0)
        try:
            if c.try_call(method, retries=0) is not None:
                return
        finally:
            c.close()
        # jittered poll: a fleet of workers waiting out one master
        # restart must not re-probe on a shared 250 ms clock
        time.sleep(_random.uniform(0.15, 0.35))
    raise RpcError(f"{service} at {address} not reachable "
                   f"after {timeout}s")
