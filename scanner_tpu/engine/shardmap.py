"""Horizontally sharded control plane: the versioned shard map
(docs/robustness.md §Sharded control plane).

One Scanner cluster can run M master *shards* instead of one master.
Bulks (and all their durable control-plane state — generation claims,
checkpoints, journals) partition across shards by consistent hash on
the admission token, so each shard is exactly the single-master
control plane PR 12/13 hardened, scoped to a namespace
(`jobs/s<shard>/...`; shard 0 keeps the legacy unprefixed layout).
Losing a shard loses nothing: a respawned master for that shard
CAS-claims the next generation *in that shard's namespace* and
`_recover_bulk` + journal replay carry over verbatim as shard
failover.

This module owns the three pieces every other layer shares:

**The ring** — `ShardMap.shard_for(key)` maps a job token onto a
shard by consistent hash over ``VNODES`` virtual points per shard,
using a *stable* digest (md5), never Python's per-process randomized
``hash()``.  Removing a dead shard's points moves only the keys that
shard owned; every other shard's assignment is untouched — the
property tests/test_shardmap.py pins.

**The durable map** — each shard publishes its address into
``jobs/shardmap/e<epoch>.bin`` via `write_exclusive` CAS
(`register_shard`); highest epoch wins, losers re-merge and retry.
Every shard serves the map over the ``GetShardMap`` RPC; clients and
workers resolve it from any shard.  The **map epoch** fences routing:
mutating RPCs may stamp the epoch of the map they routed with, and a
master whose map is newer NACKs them (``{"stale_map": True}``) so a
stale map can never route a mutation past a failover.

**The series** — every ``scanner_tpu_shard_*`` metric (and the RPC
coalescing counter the per-shard fan-out makes necessary) registers
here; SHARD_SERIES names them for scanner-check SC316, which keeps
this tuple, the registrations, and the docs/observability.md catalog
table in sync, all directions.

Sizing: ``[control] shards`` / ``SCANNER_TPU_CONTROL_SHARDS`` (env
wins, read at import).  The default of 1 is the pre-sharding cluster,
bit-for-bit: shard 0 uses the legacy paths and no map is published.
"""

from __future__ import annotations

import bisect
import hashlib
import os
import threading
from typing import Dict, List, Optional

from ..storage import metadata as md
from ..storage.backend import StorageBackend
from ..util import metrics as _mx
from ..util.log import get_logger

_log = get_logger("shardmap")

# the [control] config keys this module accepts (scanner-check SC316
# keeps config.default_config() and this tuple in sync, both ways)
CONFIG_KEYS = ("shards",)

# virtual points per shard on the hash ring: enough that keys spread
# within a few percent of uniform across single-digit shard counts
VNODES = 64

# every shard-control-plane series, registered below in this module;
# scanner-check SC316 pairs this tuple with the registrations and the
# docs/observability.md shard-series table, both directions
SHARD_SERIES = (
    "scanner_tpu_shard_id",
    "scanner_tpu_shard_count",
    "scanner_tpu_shard_map_epoch",
    "scanner_tpu_shard_stale_map_rejections_total",
    "scanner_tpu_shard_failovers_total",
    "scanner_tpu_shard_journal_reexec_total",
    "scanner_tpu_rpc_coalesced_total",
)

_M_SHARD_ID = _mx.registry().gauge(
    "scanner_tpu_shard_id",
    "This master's shard id within the sharded control plane (0 in "
    "the default single-master deployment).")
_M_SHARD_COUNT = _mx.registry().gauge(
    "scanner_tpu_shard_count",
    "Number of master shards the control plane is configured for "
    "([control] shards / SCANNER_TPU_CONTROL_SHARDS).")
_M_MAP_EPOCH = _mx.registry().gauge(
    "scanner_tpu_shard_map_epoch",
    "Epoch of the newest shard map this process has observed — the "
    "fence a stale map's mutations are NACKed against.")
_M_STALE_MAP = _mx.registry().counter(
    "scanner_tpu_shard_stale_map_rejections_total",
    "Mutating RPCs NACKed because the caller routed with a shard map "
    "older than the serving master's (the stale-map fence; the caller "
    "refreshes the map and re-routes).")
_M_FAILOVERS = _mx.registry().counter(
    "scanner_tpu_shard_failovers_total",
    "Shard failovers completed by this master: recoveries that "
    "adopted a predecessor generation's bulk in a sharded "
    "(num_shards > 1) control plane.")
_M_REEXEC = _mx.registry().counter(
    "scanner_tpu_shard_journal_reexec_total",
    "Journaled-done tasks a recovery re-queued anyway — acknowledged "
    "completions that would re-execute.  Zero by construction; the "
    "master-shard-loss chaos drill asserts it stays zero.")
_M_COALESCED = _mx.registry().counter(
    "scanner_tpu_rpc_coalesced_total",
    "Control RPCs saved by coalescing: FinishedWork completions "
    "folded into a FinishedWorkBatch, and full heartbeat payloads "
    "folded into slim liveness beats on non-active shards.",
    labels=["method"])


def _flag_int(v: Optional[str], default: int) -> int:
    if v is None or v == "":
        return default
    return int(v)


_num_shards = max(1, _flag_int(os.environ.get("SCANNER_TPU_CONTROL_SHARDS"), 1))


def num_shards() -> int:
    return _num_shards


def set_num_shards(n: int) -> None:
    """Deployment default ([control] shards); the
    SCANNER_TPU_CONTROL_SHARDS env var is read at import and wins."""
    global _num_shards
    _num_shards = max(1, int(n))


def stable_hash(key: str) -> int:
    """Process-stable 64-bit digest (md5 prefix).  Never Python's
    ``hash()``: that is salted per process, and the ring must agree
    across every client, worker, and master."""
    return int.from_bytes(
        hashlib.md5(str(key).encode("utf-8")).digest()[:8], "big")


class ShardMap:
    """One immutable version of the shard membership: ``epoch`` plus
    ``{shard_id: address}``.  Routing hashes onto the ring built from
    the shards *present* — a dead shard's entry is simply absent in
    the successor epoch until its replacement re-registers, and only
    its keys move."""

    def __init__(self, epoch: int = 0,
                 shards: Optional[Dict[int, str]] = None,
                 num_shards: Optional[int] = None):
        self.epoch = int(epoch)
        self.shards: Dict[int, str] = {
            int(k): str(v) for k, v in (shards or {}).items()}
        self.num_shards = int(
            num_shards if num_shards is not None
            else (max(self.shards) + 1 if self.shards else 1))
        self._ring_keys: List[int] = []
        self._ring_sids: List[int] = []
        pts = []
        for sid in self.shards:
            for v in range(VNODES):
                pts.append((stable_hash(f"shard{sid}#{v}"), sid))
        pts.sort()
        self._ring_keys = [p[0] for p in pts]
        self._ring_sids = [p[1] for p in pts]

    def shard_for(self, key: str) -> int:
        """Owning shard id for a routing key (admission token / job
        id).  Empty map routes to shard 0 (the legacy master)."""
        if not self._ring_keys:
            return 0
        i = bisect.bisect_right(self._ring_keys, stable_hash(key))
        if i >= len(self._ring_keys):
            i = 0
        return self._ring_sids[i]

    def address_of(self, shard_id: int) -> Optional[str]:
        return self.shards.get(int(shard_id))

    def shard_ids(self) -> List[int]:
        return sorted(self.shards)

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "num_shards": self.num_shards,
                "shards": {str(k): v for k, v in self.shards.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ShardMap":
        return cls(epoch=int(d.get("epoch", 0)),
                   shards={int(k): v
                           for k, v in (d.get("shards") or {}).items()},
                   num_shards=d.get("num_shards"))


# ---------------------------------------------------------------------------
# the durable map (CAS-published epochs on the storage backend)
# ---------------------------------------------------------------------------

# epochs below (newest - KEEP_EPOCHS) are pruned best-effort after a
# successful publish; enough history that a reader racing a publish
# never finds its epoch deleted mid-read
KEEP_EPOCHS = 8


def load(backend: StorageBackend) -> Optional[ShardMap]:
    """The newest published shard map, or None (unsharded db)."""
    best_epoch = -1
    best_path = None
    for p in backend.list_prefix(md.shardmap_prefix()):
        base = p.rsplit("/", 1)[-1]
        try:
            e = int(base.lstrip("e").split(".")[0])
        except ValueError:
            continue
        if e > best_epoch:
            best_epoch, best_path = e, p
    if best_path is None:
        return None
    try:
        return ShardMap.from_dict(md.unpack(backend.read(best_path)))
    except Exception:  # noqa: BLE001 — racing a prune, or torn write
        _log.warning("unreadable shard map at %s", best_path)
        return None


def publish(backend: StorageBackend, smap: ShardMap) -> bool:
    """CAS-publish one specific epoch: True for exactly one concurrent
    publisher (write_exclusive), False for the rest."""
    return backend.write_exclusive(
        md.shardmap_path(smap.epoch), md.pack(smap.to_dict()))


def register_shard(backend: StorageBackend, shard_id: int,
                   address: str, num_shards: int) -> ShardMap:
    """Merge this shard's address into the durable map at the next
    epoch (retrying the CAS until we win), and return the map
    published.  Startup AND failover use this: a respawned shard
    re-publishing its (possibly new) address is exactly the epoch bump
    that tells every map holder to refresh."""
    while True:
        cur = load(backend)
        shards = dict(cur.shards) if cur else {}
        shards[int(shard_id)] = str(address)
        nxt = ShardMap(epoch=(cur.epoch if cur else 0) + 1,
                       shards=shards, num_shards=num_shards)
        if publish(backend, nxt):
            _prune(backend, nxt.epoch)
            _log.info("published shard map epoch %d: shard %d -> %s",
                      nxt.epoch, shard_id, address)
            return nxt
        # lost the CAS race: another shard registered concurrently;
        # re-load so its entry survives the merge, take the next epoch


def _prune(backend: StorageBackend, newest: int) -> None:
    try:
        for p in backend.list_prefix(md.shardmap_prefix()):
            base = p.rsplit("/", 1)[-1]
            try:
                e = int(base.lstrip("e").split(".")[0])
            except ValueError:
                continue
            if e <= newest - KEEP_EPOCHS:
                backend.delete(p)
    except Exception:  # noqa: BLE001 — pruning is best-effort
        pass


# ---------------------------------------------------------------------------
# metric hooks (the one place the shard series are touched from)
# ---------------------------------------------------------------------------

def note_identity(shard_id: int, num_shards_: int) -> None:
    _M_SHARD_ID.set(int(shard_id))
    _M_SHARD_COUNT.set(int(num_shards_))


def note_map_epoch(epoch: int) -> None:
    _M_MAP_EPOCH.set(int(epoch))


def count_stale_map_rejection() -> None:
    _M_STALE_MAP.inc()


def count_failover() -> None:
    _M_FAILOVERS.inc()


def count_journal_reexec(n: int) -> None:
    if n:
        _M_REEXEC.inc(int(n))


def count_coalesced(method: str, n: int = 1) -> None:
    if n > 0:
        _M_COALESCED.labels(method=method).inc(int(n))


class MapHolder:
    """Thread-safe 'newest map I have seen' cell shared by a worker's
    heartbeat and pull loops (and the client's admission/poll loops).
    ``observe`` adopts strictly newer epochs only."""

    def __init__(self, smap: Optional[ShardMap] = None):
        self._lock = threading.Lock()
        self._map = smap

    def get(self) -> Optional[ShardMap]:
        with self._lock:
            return self._map

    def epoch(self) -> int:
        with self._lock:
            return self._map.epoch if self._map else 0

    def observe(self, smap: Optional[ShardMap]) -> bool:
        """Adopt a newer map; True when it replaced the held one."""
        if smap is None:
            return False
        with self._lock:
            if self._map is None or smap.epoch > self._map.epoch:
                self._map = smap
                return True
        return False
